"""Structured per-call tracing: event spans + Perfetto export.

Every collective call carries a :class:`TraceSpan` through the whole
stack — submit (driver), queue-enter (request layer), gang-ready and
dispatch (backend gang scheduler, lane-tagged: leader / executor /
batched / emu), device-begin/end (compiled program window), and
callback-complete.  Spans from every rank of an in-process world land
in one bounded ring buffer (:class:`TraceCollector`) and export as
Chrome/Perfetto ``trace_event`` JSON: one process (pid) per rank, one
track (tid) per stage/lane, and gang members share a gang id so a
fused gang program shows as one aligned slice across ranks.

Reference analogs: the hardware exposes only a per-call cycle counter
(get_duration, SURVEY §5) — this layer is the per-stage breakdown
ACCL+ (arxiv 2312.11742) motivates, built in rather than bolted onto
each bench.

Overhead discipline: tracing is OFF unless ``ACCL_TRACE`` is set
(``1`` = collect, any other non-``0`` value = collect and dump to that
path at exit).  When off, :func:`enabled` is a module-bool read and
:func:`new_span` is never called — the instrumented hot paths allocate
nothing (tests/test_observability.py pins this).

Device timelines (r15): the ``ACCL_DEVICE_TRACE`` Pallas ring kernels
(ops/ring.py) write per-step stamp rows — :data:`DEVICE_TRACE_FIELDS`
— into an extra kernel output; :func:`record_device_steps` lands them
here via ``jax.debug.callback`` and :meth:`TraceCollector.to_perfetto`
renders them as per-rank ``device:<collective>`` tracks next to the
host spans.  Stamps are LOGICAL event-order clocks (Pallas exposes no
cycle counter): one unit = one in-kernel phase boundary, anchored at
the host-side arrival time of the stamp buffer.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Iterator, List, Optional

#: per-step stamp-row schema of the ACCL_DEVICE_TRACE kernel output
#: (ops/ring.py writes rows in exactly this column order): the virtual
#: rank, the ring step, three logical phase stamps (send-issue,
#: recv/ack-wait done, reduce/copy done), the two ring neighbors, and
#: the per-neighbor byte counts of the step
DEVICE_TRACE_FIELDS = (
    "rank", "step", "seq_send", "seq_wait", "seq_phase",
    "tx_peer", "rx_peer", "tx_bytes", "rx_bytes",
)

#: monotonic nanosecond clock shared by every instrumentation point —
#: comparable across threads of one process, which is exactly the
#: in-process multi-rank world the collector merges
now_ns = time.perf_counter_ns

_lock = threading.Lock()
_enabled = False
_dump_path: Optional[str] = None
_collector: Optional["TraceCollector"] = None
_atexit_armed = False


class TraceSpan:
    """One call's event record: monotonic ns timestamps per stage.

    Unset stages stay None (e.g. gang-ready on the emulator backend,
    whose native engine matches calls below the Python layer); export
    skips slices whose endpoints are missing."""

    __slots__ = ("name", "desc", "rank", "gang_id", "lane", "tenant",
                 "count", "dtype", "nbytes", "nranks", "t_submit",
                 "t_queue", "t_gang_ready", "t_dispatch",
                 "t_device_begin", "t_device_end", "t_complete")

    def __init__(self, name: str, desc: str = "", rank: int = -1,
                 count: int = 0, dtype: str = "", nbytes: int = 0,
                 nranks: int = 1):
        self.name = name
        self.desc = desc
        self.rank = rank
        self.gang_id: Optional[int] = None
        self.lane: Optional[str] = None
        #: tenant/lane label of the issuing communicator (r20) — spans
        #: of a labeled tenant render on their own per-tenant call track
        self.tenant: Optional[str] = None
        self.count = count
        self.dtype = dtype
        self.nbytes = nbytes
        self.nranks = nranks
        self.t_submit: Optional[int] = None
        self.t_queue: Optional[int] = None
        self.t_gang_ready: Optional[int] = None
        self.t_dispatch: Optional[int] = None
        self.t_device_begin: Optional[int] = None
        self.t_device_end: Optional[int] = None
        self.t_complete: Optional[int] = None

    def timestamps(self) -> dict:
        return {k: getattr(self, "t_" + k) for k in (
            "submit", "queue", "gang_ready", "dispatch", "device_begin",
            "device_end", "complete")}

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return (f"TraceSpan({self.name!r}, rank={self.rank}, "
                f"gang={self.gang_id}, lane={self.lane})")


class TraceCollector:
    """Bounded ring buffer of completed spans + the gang-id registry.

    Gang ids pair up the per-rank spans of one collective *instance*:
    rank R's Nth call with a given (op, comm, tag, root) signature
    belongs to the same gang as every other rank's Nth call with that
    signature — the same FIFO-per-key discipline the TPU backend's gang
    assembly and the emulator's rx seek both implement, so the
    driver-level assignment matches what the engines actually pair."""

    def __init__(self, capacity: int = 65536):
        self.capacity = capacity
        self._spans: deque = deque(maxlen=capacity)
        #: device stamp-buffer records (r15): one entry per traced
        #: kernel invocation — {"collective", "base_ns", "rows"}
        self._device: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._gang_seq = 0
        # (key, occurrence) -> gang id; bounded so an unbounded run
        # cannot grow the table past the ring buffer's usefulness
        self._gang_ids: OrderedDict = OrderedDict()
        self._occurrence: dict = {}

    # -- span intake ---------------------------------------------------
    def add(self, span: TraceSpan) -> None:
        with self._lock:
            self._spans.append(span)

    def gang_id_for(self, key: tuple, rank: int) -> int:
        """Gang id of `rank`'s next occurrence of call signature `key`."""
        with self._lock:
            n = self._occurrence.get((key, rank), 0)
            self._occurrence[(key, rank)] = n + 1
            gid = self._gang_ids.get((key, n))
            if gid is None:
                gid = self._gang_seq
                self._gang_seq += 1
                self._gang_ids[(key, n)] = gid
                while len(self._gang_ids) > 4 * self.capacity:
                    self._gang_ids.popitem(last=False)
            return gid

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._device.clear()
            self._gang_ids.clear()
            self._occurrence.clear()

    def spans(self) -> list:
        with self._lock:
            return list(self._spans)

    # -- device stamp buffers (r15, ACCL_DEVICE_TRACE) -----------------
    def add_device_steps(self, collective: str, rows: List[list],
                         base_ns: Optional[int] = None) -> None:
        """One traced kernel invocation's stamp rows (DEVICE_TRACE_
        FIELDS order), anchored at ``base_ns`` (host arrival time by
        default — the stamps themselves are logical event counters)."""
        with self._lock:
            self._device.append({
                "collective": collective,
                "base_ns": base_ns if base_ns is not None else now_ns(),
                "rows": [list(map(int, r)) for r in rows],
            })

    def device_records(self) -> list:
        with self._lock:
            return list(self._device)

    def device_link_bytes(self) -> dict:
        """Per-neighbor byte counts folded out of the stamp buffers:
        {(rank, peer): tx_bytes} — the device-side half of the link
        matrix (the emu/tpu engine twins measure the host side)."""
        out: dict = {}
        for rec in self.device_records():
            for row in rec["rows"]:
                r = dict(zip(DEVICE_TRACE_FIELDS, row))
                key = (r["rank"], r["tx_peer"])
                out[key] = out.get(key, 0) + r["tx_bytes"]
        return out

    def __len__(self) -> int:
        return len(self._spans)

    # -- export --------------------------------------------------------
    def to_perfetto(self) -> dict:
        """Chrome/Perfetto ``trace_event`` JSON object.

        Track layout: pid = rank (process_name metadata "rank N"), tids
        are per-rank stage tracks — ``call`` (submit→complete), ``queue``
        (queue-enter→dispatch, with the gang-ready boundary in args),
        and one ``lane:<name>`` track per dispatch lane holding the
        device-begin→device-end slice.  Gang members carry the same
        ``gang#<id>`` slice name and (for fused gang programs, whose
        device window is measured once per gang) identical ts/dur — the
        aligned cross-rank slice a Perfetto timeline groups visually."""
        events: list = []
        tids: dict = {}
        procs: set = set()

        def tid(pid: int, label: str) -> int:
            key = (pid, label)
            t = tids.get(key)
            if t is None:
                t = len([1 for k in tids if k[0] == pid])
                tids[key] = t
                events.append({"name": "thread_name", "ph": "M", "ts": 0,
                               "pid": pid, "tid": t,
                               "args": {"name": label}})
            return t

        def slice_ev(pid: int, label: str, name: str, t0, t1, args):
            if t0 is None or t1 is None:
                return
            events.append({
                "name": name, "ph": "X", "cat": "accl",
                "ts": t0 / 1e3, "dur": max(t1 - t0, 0) / 1e3,
                "pid": pid, "tid": tid(pid, label), "args": args,
            })

        for s in self.spans():
            pid = s.rank if s.rank >= 0 else 9999
            if pid not in procs:
                procs.add(pid)
                events.append({
                    "name": "process_name", "ph": "M", "ts": 0, "pid": pid,
                    "tid": 0, "args": {"name": (
                        f"rank {pid}" if pid != 9999 else "host")}})
            gid = f" gang#{s.gang_id}" if s.gang_id is not None else ""
            args = {"desc": s.desc, "count": s.count, "dtype": s.dtype,
                    "nbytes": s.nbytes, "nranks": s.nranks,
                    "gang_id": s.gang_id, "lane": s.lane,
                    "tenant": s.tenant,
                    "timestamps_ns": s.timestamps()}
            call_track = ("call" if s.tenant is None
                          else f"call:{s.tenant}")
            slice_ev(pid, call_track, s.name + gid, s.t_submit,
                     s.t_complete, args)
            slice_ev(pid, "queue", s.name + gid, s.t_queue,
                     s.t_dispatch or s.t_complete,
                     {"gang_ready_ns": s.t_gang_ready})
            if s.lane is not None:
                slice_ev(pid, f"lane:{s.lane}", s.name + gid,
                         s.t_device_begin or s.t_dispatch,
                         s.t_device_end or s.t_complete, args)
        # device stamp-buffer tracks (r15): one `device:<collective>`
        # track per rank; each step renders its transfer window
        # (send-issue -> recv/ack-wait done) and its reduce/copy window
        # as consecutive slices on the logical stamp clock (1 stamp
        # unit = 1 us), anchored at the buffer's host arrival time
        for rec in self.device_records():
            base = rec["base_ns"]
            coll = rec["collective"]
            for row in rec["rows"]:
                r = dict(zip(DEVICE_TRACE_FIELDS, row))
                pid = r["rank"]
                if pid not in procs:
                    procs.add(pid)
                    events.append({
                        "name": "process_name", "ph": "M", "ts": 0,
                        "pid": pid, "tid": 0,
                        "args": {"name": f"rank {pid}"}})
                label = f"device:{coll}"
                t0 = base + r["seq_send"] * 1000
                t1 = base + r["seq_wait"] * 1000
                t2 = base + r["seq_phase"] * 1000
                slice_ev(pid, label,
                         f"s{r['step']}:xfer->r{r['tx_peer']}", t0, t1,
                         {"step": r["step"], "tx_peer": r["tx_peer"],
                          "rx_peer": r["rx_peer"],
                          "tx_bytes": r["tx_bytes"],
                          "rx_bytes": r["rx_bytes"],
                          "device_track": True,
                          "device_phase": "xfer"})
                slice_ev(pid, label, f"s{r['step']}:reduce", t1, t2,
                         {"step": r["step"], "device_track": True,
                          "device_phase": "reduce"})
        return {"traceEvents": events, "displayTimeUnit": "ns"}

    def dump(self, path: str) -> str:
        """Write the Perfetto JSON; returns the path."""
        with open(path, "w") as f:
            json.dump(self.to_perfetto(), f)
        return path


# ---------------------------------------------------------------------------
# module state: enable/disable + singleton collector
# ---------------------------------------------------------------------------
def _init_from_env() -> None:
    raw = os.environ.get("ACCL_TRACE", "")
    if raw and raw != "0":
        enable(None if raw == "1" else raw)


def enable(dump_path: Optional[str] = None,
           capacity: Optional[int] = None) -> "TraceCollector":
    """Turn tracing on; with `dump_path`, the Perfetto JSON is written
    there at interpreter exit (the ACCL_TRACE=<path> behavior)."""
    global _enabled, _dump_path, _collector, _atexit_armed
    with _lock:
        if _collector is None or (capacity is not None
                                  and _collector.capacity != capacity):
            _collector = TraceCollector(
                capacity or int(os.environ.get("ACCL_TRACE_CAP", "65536")))
        _enabled = True
        _dump_path = dump_path
        if dump_path and not _atexit_armed:
            import atexit

            atexit.register(_dump_at_exit)
            _atexit_armed = True
        return _collector


def disable() -> None:
    global _enabled
    with _lock:
        _enabled = False


def _dump_at_exit() -> None:  # pragma: no cover — exercised by CI smoke
    if _enabled and _dump_path and _collector is not None:
        try:
            _collector.dump(_dump_path)
        except OSError:
            pass


def enabled() -> bool:
    """Fast gate every instrumentation point checks first — a module
    bool read, so the disabled path costs one attribute lookup."""
    return _enabled


def collector() -> TraceCollector:
    global _collector
    with _lock:
        if _collector is None:
            _collector = TraceCollector(
                int(os.environ.get("ACCL_TRACE_CAP", "65536")))
        return _collector


def record_device_steps(collective: str, buf) -> None:
    """Land one ACCL_DEVICE_TRACE stamp buffer in the collector — the
    ``jax.debug.callback`` target ops/ring.py arms after each traced
    ``pallas_call``.  ``buf`` is the kernel's (steps, len(DEVICE_TRACE_
    FIELDS)) int32 output (a leading shard/batch dim is flattened).
    Never raises: a malformed buffer must not take the workload down."""
    try:
        import numpy as np

        arr = np.asarray(buf).reshape(-1, len(DEVICE_TRACE_FIELDS))
        collector().add_device_steps(collective, arr.tolist())
    except Exception:  # noqa: BLE001 — observability must stay passive
        pass


def new_span(name: str, desc: str = "", rank: int = -1, count: int = 0,
             dtype: str = "", nbytes: int = 0,
             nranks: int = 1) -> Optional[TraceSpan]:
    """Allocate a span for one call — returns None when tracing is off,
    so callers hold the no-allocation fast path with one check."""
    if not _enabled:
        return None
    return TraceSpan(name, desc, rank, count, dtype, nbytes, nranks)


# ---------------------------------------------------------------------------
# marked windows + XLA profiler integration
# ---------------------------------------------------------------------------
@contextlib.contextmanager
def traced_window(label: str,
                  xla_logdir: Optional[str] = None) -> Iterator[None]:
    """Mark a host window in the trace; with `xla_logdir` (or the
    ACCL_XLA_TRACE env var) also capture an XLA profiler trace of the
    window via utils/profiling.xla_trace, so the Perfetto timeline and
    the XLA/TensorBoard capture cover the same marked region."""
    logdir = xla_logdir or os.environ.get("ACCL_XLA_TRACE", "")
    span = new_span(f"window:{label}")
    if span is not None:
        span.t_submit = span.t_queue = span.t_dispatch = now_ns()
        span.lane = "window"
    try:
        if logdir:
            from ..utils.profiling import xla_trace

            with xla_trace(logdir):
                yield
        else:
            yield
    finally:
        if span is not None:
            span.t_device_begin = span.t_submit
            span.t_device_end = span.t_complete = now_ns()
            collector().add(span)


# ---------------------------------------------------------------------------
# multi-process merge
# ---------------------------------------------------------------------------
def salvage_torn_json(text: str, list_key: str) -> tuple:
    """Best-effort parse of a JSON document truncated mid-write (a
    crash-time dump: the process died inside ``json.dump``).  Finds the
    ``"<list_key>": [`` array and decodes its elements one by one until
    the torn tail, reconstructing ``{<scalars before the array>,
    <list_key>: [complete elements]}``.  Returns ``(doc, skipped_tail_
    bytes)`` — the caller reports the skip instead of raising, so ONE
    rank's torn dump cannot take the whole post-mortem merge down.
    Raises ValueError only when not even the array start is present."""
    import re

    decoder = json.JSONDecoder()
    m = re.search(r'"%s"\s*:\s*\[' % re.escape(list_key), text)
    if m is None:
        raise ValueError(f"no {list_key!r} array found in torn document")
    # scalar fields before the array (rank/capacity/... or nothing)
    doc: dict = {}
    for sm in re.finditer(
            r'"([A-Za-z0-9_]+)"\s*:\s*(-?\d+(?:\.\d+)?|"(?:[^"\\]|\\.)*"'
            r'|true|false|null)\s*,', text[:m.start()]):
        try:
            doc[sm.group(1)] = json.loads(sm.group(2))
        except json.JSONDecodeError:  # pragma: no cover — regex-vetted
            continue
    items: list = []
    pos = m.end()
    n = len(text)
    while True:
        while pos < n and text[pos] in " \t\r\n,":
            pos += 1
        if pos >= n or text[pos] == "]":
            break
        try:
            item, end = decoder.raw_decode(text, pos)
        except json.JSONDecodeError:
            break  # the torn tail starts here
        items.append(item)
        pos = end
    doc[list_key] = items
    return doc, max(n - pos, 0)


def merge_trace_files(paths, out_path: Optional[str] = None) -> dict:
    """Merge per-process trace files (e.g. one per multihost rank) into
    one timeline, aligning clocks by shared gang ids: each file is
    shifted so the device-begin of the first gang it shares with the
    reference file coincides — the cross-rank alignment an in-process
    world gets for free from the shared monotonic clock."""
    merged: list = []
    ref_gangs: dict = {}
    torn: list = []
    seen_meta: set = set()
    for i, path in enumerate(paths):
        with open(path) as f:
            text = f.read()
        try:
            events = json.loads(text).get("traceEvents", [])
        except json.JSONDecodeError:
            # crash-time dump truncated mid-record (r14 satellite):
            # salvage the complete prefix, skip the torn tail with a
            # warning + a count in the merged doc — one dead rank must
            # not take the whole post-mortem timeline down
            doc_part, skipped = salvage_torn_json(text, "traceEvents")
            events = doc_part.get("traceEvents", [])
            torn.append({"path": str(path),
                         "events_recovered": len(events),
                         "tail_bytes_skipped": skipped})
            from ..utils.logging import get_logger

            get_logger("accl_tpu.trace").warning(
                "trace file %s is truncated mid-record — salvaged %d "
                "event(s), skipped %d torn tail byte(s)",
                path, len(events), skipped)
        gangs = {}
        for ev in events:
            args = ev.get("args") or {}
            gid = args.get("gang_id")
            if gid is None or ev.get("ph") != "X" or gid in gangs:
                continue
            # anchor on the DEVICE window, not the slice ts: the call
            # slice starts at the rank-local submit time, and shifting
            # by that would absorb exactly the cross-rank submit skew
            # the merged timeline exists to reveal — a fused gang's
            # device-begin is the instant genuinely shared across ranks
            dev0 = (args.get("timestamps_ns") or {}).get("device_begin")
            anchor = dev0 / 1e3 if dev0 else ev["ts"]
            if anchor > 0:
                gangs[gid] = anchor
        offset = 0.0
        if i == 0:
            ref_gangs = gangs
        else:
            shared = sorted(set(gangs) & set(ref_gangs))
            if shared:
                g = shared[0]
                offset = ref_gangs[g] - gangs[g]
        for ev in events:
            if ev.get("ph") == "X":
                ev = dict(ev, ts=ev["ts"] + offset)
            elif ev.get("ph") == "M":
                # metadata dedup (r15 satellite): every input file
                # re-emits its own thread_name/process_name rows, so a
                # merge used to carry one copy per file for the same
                # (pid, tid) — Perfetto renders duplicated track names.
                # Keep the FIRST declaration per (event, pid, tid).
                mkey = (ev.get("name"), ev.get("pid"), ev.get("tid"))
                if mkey in seen_meta:
                    continue
                seen_meta.add(mkey)
            merged.append(ev)
    doc = {"traceEvents": merged, "displayTimeUnit": "ns"}
    if torn:
        doc["torn_files"] = torn
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f)
    return doc


_init_from_env()
