"""End-to-end observability: per-call trace spans (Perfetto export),
the metrics registry both backends and the bench harnesses publish
into, the always-on flight recorder, the hang watchdog + health/
OpenMetrics surface, and the r14 performance observatory — cross-rank
critical-path attribution, the native-engine telemetry sampler, and
the continuous regression sentinel.  See docs/observability.md and
docs/debugging.md for usage."""

from .attribution import (  # noqa: F401
    attribute,
    estimate_clock_skew,
)
from .flight import (  # noqa: F401
    FlightRecord,
    FlightRecorder,
    dump_all as dump_all_flight,
    enabled as flight_enabled,
    merge_flight_dumps,
)
from .health import (  # noqa: F401
    HEALTH_DEGRADED,
    HEALTH_HUNG,
    HEALTH_OK,
    HEALTH_SLOW,
    MetricsExporter,
    Watchdog,
    exporter_port,
    start_exporter,
    stop_exporter,
)
from .metrics import (  # noqa: F401
    LATENCY_BUCKETS_US,
    MetricsRegistry,
    busbw_factor,
    default_registry,
    dump_metrics,
    metric_help_for,
    payload_factor,
    size_bucket,
    validate_openmetrics,
)
from .sentinel import (  # noqa: F401
    Baseline,
    Sentinel,
)
from .telemetry import (  # noqa: F401
    ENGINE_STATS_FIELDS_V1,
    TelemetrySampler,
)
from .trace import (  # noqa: F401
    TraceCollector,
    TraceSpan,
    collector,
    disable as disable_tracing,
    enable as enable_tracing,
    enabled as tracing_enabled,
    merge_trace_files,
    new_span,
    traced_window,
)
