"""End-to-end observability: per-call trace spans (Perfetto export),
the metrics registry both backends and the bench harnesses publish
into, the always-on flight recorder, and the hang watchdog + health/
OpenMetrics surface.  See docs/observability.md and docs/debugging.md
for usage."""

from .flight import (  # noqa: F401
    FlightRecord,
    FlightRecorder,
    dump_all as dump_all_flight,
    enabled as flight_enabled,
    merge_flight_dumps,
)
from .health import (  # noqa: F401
    HEALTH_DEGRADED,
    HEALTH_HUNG,
    HEALTH_OK,
    MetricsExporter,
    Watchdog,
    start_exporter,
    stop_exporter,
)
from .metrics import (  # noqa: F401
    LATENCY_BUCKETS_US,
    MetricsRegistry,
    busbw_factor,
    default_registry,
    dump_metrics,
    payload_factor,
    size_bucket,
)
from .trace import (  # noqa: F401
    TraceCollector,
    TraceSpan,
    collector,
    disable as disable_tracing,
    enable as enable_tracing,
    enabled as tracing_enabled,
    merge_trace_files,
    new_span,
    traced_window,
)
