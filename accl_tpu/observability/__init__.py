"""End-to-end observability: per-call trace spans (Perfetto export)
and the metrics registry both backends and the bench harnesses publish
into.  See docs/observability.md for usage."""

from .trace import (  # noqa: F401
    TraceCollector,
    TraceSpan,
    collector,
    disable as disable_tracing,
    enable as enable_tracing,
    enabled as tracing_enabled,
    merge_trace_files,
    new_span,
    traced_window,
)
from .metrics import (  # noqa: F401
    LATENCY_BUCKETS_US,
    MetricsRegistry,
    busbw_factor,
    default_registry,
    dump_metrics,
    payload_factor,
    size_bucket,
)
