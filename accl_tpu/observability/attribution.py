"""Cross-rank critical-path attribution over merged observability dumps.

r7/r8 record *what happened* (trace spans, flight records); this module
answers the question nobody could: **which rank made this collective
slow, and where did the time go** — queued on the host, waiting for the
gang to assemble, dispatching, or on the wire/reduce path.  The method
is the per-stage latency decomposition ACCL+ (arxiv 2312.11742) applies
to its offload engine, lifted to the cross-rank setting: it is the
measurement substrate the HiCCL-style autotuner (ROADMAP item 2, arxiv
2408.05962) and the QoS/SLO serving lanes (item 4) consume.

Method
------
1. **Gang pairing** — per communicator, rank R's Nth *completed* gang
   record with signature (collective, tag, count, dtype) belongs to the
   same gang instance as every other rank's Nth record with that
   signature: the FIFO-per-key discipline the engines' own gang
   assembly implements (and trace.TraceCollector.gang_id_for mirrors).
2. **Clock-skew estimation** — per-rank timestamps are monotonic and
   *rank-local* (distinct processes = distinct clocks).  Every member
   of a gang instance shares a synchronization point: the instance's
   completion (an allreduce's result cannot exist on any rank before
   the rendezvous resolved), so per-rank offsets are estimated as the
   MEDIAN over shared gang instances of (rank's completion − reference
   rank's completion) and subtracted before any cross-rank comparison.
   In-process worlds share one clock and the estimate collapses to the
   (small) completion-publication jitter; attribution subtracts it
   anyway so the same code serves merged multi-process dumps.
3. **Phase decomposition** — consecutive intervals partitioning each
   record's submit→complete span (they sum to the span by
   construction; the acceptance test pins coverage ≥ 95%):
   ``queue`` (submit→queue: descriptor staging + request queue),
   ``gang_wait`` (own arrival → the LAST member's skew-corrected
   arrival — zero for the straggler itself), ``dispatch`` (gang-ready →
   dispatch where the backend stamps it), and ``wire`` (everything
   after the gang assembled: transport + reduction).  When a Perfetto
   trace doc is supplied, the device window splits ``wire`` into
   ``wire`` (pre-device) and ``reduce`` (device-begin→device-end).
4. **Straggler attribution** — per gang instance the last-arriving
   rank, its lateness vs the first arrival, aggregated per
   (collective, comm, size-bucket): episode counts, share, mean/max
   lateness, and the dominant straggler when one rank owns the
   majority of episodes.

Inputs are merged flight docs (:func:`flight.merge_flight_dumps`
output), per-rank dump dicts/paths, or anything ``merge_flight_dumps``
accepts — including crash-truncated dumps, which the r14 tolerant
loader salvages.  ``scripts/perf_doctor.py`` is the CLI.
"""
from __future__ import annotations

from typing import Optional

from .metrics import size_bucket

#: arrival must trail the first rank by at least this to count as a
#: straggler episode (below it, arrival order is scheduler noise)
DEFAULT_LATE_FLOOR_US = 5.0

#: phases, in span order (reduce only materializes with a trace doc)
PHASES = ("queue", "gang_wait", "dispatch", "wire", "reduce")


def _median(vals: list) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return float(s[mid]) if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _ensure_merged(dumps) -> dict:
    """Accept a merged doc, one dump dict, or an iterable of dump
    dicts/paths; always return the merged+analyzed document."""
    from .flight import merge_flight_dumps

    if isinstance(dumps, dict):
        if "ranks" in dumps:
            return dumps
        return merge_flight_dumps([dumps])
    return merge_flight_dumps(list(dumps))


def _gang_instances(doc: dict) -> dict:
    """(comm, collective, tag, count, dtype, occurrence) -> {rank: rec}
    over COMPLETED gang records, per the FIFO-per-key pairing."""
    instances: dict = {}
    for rd in doc["ranks"]:
        rank = rd["rank"]
        occurrence: dict = {}
        for rec in sorted(rd["records"], key=lambda x: x["seq"]):
            if not rec.get("gang") or rec["state"] != "complete":
                continue
            key = (rec["comm"], rec["collective"], rec["tag"],
                   rec["count"], rec["dtype"])
            n = occurrence.get(key, 0)
            occurrence[key] = n + 1
            instances.setdefault(key + (n,), {})[rank] = rec
    return instances


def estimate_clock_skew(instances: dict, ranks: list) -> dict:
    """Per-rank clock offset (ns, relative to the lowest rank present)
    from gang-rendezvous completion anchors: median over shared gang
    instances of (rank's t_complete − reference's t_complete).  Ranks
    sharing no gang with the reference keep offset 0 (nothing to align
    on — their comparisons are flagged by the caller via coverage)."""
    if not ranks:
        return {}
    ref = ranks[0]
    skew = {ref: 0.0}
    for r in ranks[1:]:
        deltas = [members[r]["t_complete"] - members[ref]["t_complete"]
                  for members in instances.values()
                  if r in members and ref in members
                  and members[r]["t_complete"]
                  and members[ref]["t_complete"]]
        skew[r] = _median(deltas)
    return skew


def _arrival_ns(rec: dict) -> Optional[int]:
    """A record's gang-arrival anchor: the queue stamp (descriptor
    entering the gang scheduler / engine), falling back to dispatch
    then submit for records whose earlier stamps predate bring-up."""
    for k in ("t_queue", "t_dispatch", "t_submit"):
        t = rec.get(k)
        if t:
            return int(t)
    return None


def _device_windows(trace_doc: Optional[dict]) -> dict:
    """(rank, collective, occurrence) -> (device_begin, device_end)
    from a Perfetto doc's lane slices (trace.TraceCollector schema)."""
    if not trace_doc:
        return {}
    # multiple tracks (call / queue / lane) carry the SAME span, so the
    # same device window repeats across consecutive events: collapse
    # identical repeats per (rank, collective) and number the distinct
    # windows — occurrence i is the i-th real device execution
    per: dict = {}
    for ev in trace_doc.get("traceEvents", []):
        args = ev.get("args") or {}
        ts = (args.get("timestamps_ns") or {})
        if ev.get("ph") != "X" or not ts.get("device_begin") \
                or not ts.get("device_end"):
            continue
        rank = ev.get("pid", -1)
        coll = ev.get("name", "").split(" ")[0]
        win = (ts["device_begin"], ts["device_end"])
        lst = per.setdefault((rank, coll), [])
        if win not in lst:
            lst.append(win)
    return {(r, c, i): w
            for (r, c), lst in per.items() for i, w in enumerate(lst)}


def attribute(dumps, trace_doc: Optional[dict] = None,
              late_floor_us: float = DEFAULT_LATE_FLOOR_US,
              timeline: bool = False) -> dict:
    """Full critical-path attribution report over merged dumps.

    Returns::

        {"nranks", "reference_rank", "clock_skew_ns": {rank: ns},
         "gangs_analyzed": N,
         "collectives": {"<coll>|comm<k>|<bucket>": {
             "collective", "comm", "size_bucket", "episodes",
             "span_us", "phases_us": {phase: mean}, "phase_coverage",
             "stragglers": {rank: {"episodes", "share",
                                   "mean_late_us", "max_late_us"}},
             "dominant_straggler": {...} | None}},
         "timeline": [...]}      # per-gang detail when timeline=True
    """
    doc = _ensure_merged(dumps)
    ranks = sorted(rd["rank"] for rd in doc["ranks"])
    instances = _gang_instances(doc)
    skew = estimate_clock_skew(instances, ranks)
    windows = _device_windows(trace_doc)
    win_seen: dict = {}

    groups: dict = {}
    gang_rows: list = []
    for key, members in sorted(instances.items()):
        comm, coll, tag, count, dtype, occ = key
        if len(members) < 2:
            continue  # single-rank view: no cross-rank attribution
        # skew-corrected arrivals -> last/first arrival of the instance
        arrivals = {}
        for r, rec in members.items():
            t = _arrival_ns(rec)
            if t is not None:
                arrivals[r] = t - skew.get(r, 0.0)
        if len(arrivals) < 2:
            continue
        first_t = min(arrivals.values())
        last_rank, last_t = max(arrivals.items(), key=lambda kv: kv[1])
        late_us = (last_t - first_t) / 1e3

        nbytes = max(rec.get("nbytes", 0) for rec in members.values())
        gkey = (coll, comm, size_bucket(nbytes))
        g = groups.setdefault(gkey, {
            "episodes": 0, "span_us": 0.0,
            "phases_us": dict.fromkeys(PHASES, 0.0),
            "phase_samples": 0,
            "late": {}, "late_total": 0})
        g["episodes"] += 1

        # per-rank phase decomposition: consecutive intervals over
        # submit→complete (clamped monotonic so they PARTITION the span)
        for r, rec in members.items():
            t_sub = rec.get("t_submit") or 0
            t_cmp = rec.get("t_complete") or 0
            if not t_sub or not t_cmp or t_cmp <= t_sub:
                continue
            own_arrival = arrivals.get(r)
            # the last arrival in this rank's clock domain
            last_local = (last_t + skew.get(r, 0.0)
                          if own_arrival is not None else None)
            cuts = [t_sub]

            def cut(t):
                cuts.append(min(max(int(t), cuts[-1]), t_cmp))

            cut(rec.get("t_queue") or t_sub)             # -> queue
            cut(last_local if last_local is not None      # -> gang_wait
                else (rec.get("t_gang_ready") or cuts[-1]))
            cut(max(rec.get("t_dispatch") or 0, cuts[-1]))  # -> dispatch
            wkey = (r, coll, win_seen.get((r, coll, "n"), 0))
            dev = windows.get(wkey)
            if dev:
                cut(dev[0])                               # -> wire
                cut(dev[1])                               # -> reduce
            else:
                cut(t_cmp)                                # wire = rest
                cut(t_cmp)                                # reduce = 0
            cuts.append(t_cmp)
            # intervals: queue, gang_wait, dispatch, wire, reduce, tail
            ivals = [cuts[i + 1] - cuts[i] for i in range(len(cuts) - 1)]
            # fold the post-device tail into wire (completion callback)
            phases = {
                "queue": ivals[0],
                "gang_wait": ivals[1],
                "dispatch": ivals[2],
                "wire": ivals[3] + ivals[5],
                "reduce": ivals[4],
            }
            span = t_cmp - t_sub
            g["span_us"] += span / 1e3
            g["phase_samples"] += 1
            for p, v in phases.items():
                g["phases_us"][p] += v / 1e3
        if windows:
            for r in members:
                win_seen[(r, coll, "n")] = \
                    win_seen.get((r, coll, "n"), 0) + 1

        # straggler episode
        if late_us >= late_floor_us:
            st = g["late"].setdefault(last_rank,
                                      {"episodes": 0, "total_us": 0.0,
                                       "max_us": 0.0})
            st["episodes"] += 1
            st["total_us"] += late_us
            st["max_us"] = max(st["max_us"], late_us)
            g["late_total"] += 1
        if timeline:
            gang_rows.append({
                "collective": coll, "comm": comm, "tag": tag,
                "count": count, "dtype": dtype, "occurrence": occ,
                "arrival_rel_us": {str(r): round((t - first_t) / 1e3, 2)
                                   for r, t in sorted(arrivals.items())},
                "last_rank": last_rank,
                "lateness_us": round(late_us, 2),
            })

    collectives: dict = {}
    for (coll, comm, bucket), g in sorted(groups.items()):
        n = max(g["phase_samples"], 1)
        span = g["span_us"] / n
        phases = {p: round(v / n, 2) for p, v in g["phases_us"].items()}
        stragglers = {}
        dominant = None
        for r, st in sorted(g["late"].items()):
            share = st["episodes"] / g["late_total"] if g["late_total"] \
                else 0.0
            row = {"episodes": st["episodes"], "share": round(share, 3),
                   "mean_late_us": round(st["total_us"] / st["episodes"],
                                         2),
                   "max_late_us": round(st["max_us"], 2)}
            stragglers[str(r)] = row
            if dominant is None or share > dominant["share"]:
                dominant = {"rank": r, **row}
        collectives[f"{coll}|comm{comm}|{bucket}"] = {
            "collective": coll, "comm": comm, "size_bucket": bucket,
            "episodes": g["episodes"],
            "span_us": round(span, 2),
            "phases_us": phases,
            # phases partition submit->complete by construction; the
            # ratio is the self-check the acceptance test pins (>=0.95)
            "phase_coverage": round(sum(phases.values()) / span, 4)
            if span > 0 else 1.0,
            "straggler_episodes": g["late_total"],
            "stragglers": stragglers,
            "dominant_straggler": dominant,
        }

    report = {
        "nranks": len(ranks),
        "reference_rank": ranks[0] if ranks else -1,
        "clock_skew_ns": {str(r): round(skew.get(r, 0.0), 1)
                          for r in ranks},
        "gangs_analyzed": sum(g["episodes"] for g in groups.values()),
        "collectives": collectives,
    }
    if timeline:
        report["timeline"] = gang_rows
    return report


def _compute_windows(trace_doc: Optional[dict]) -> dict:
    """rank -> sorted, MERGED [t0_ns, t1_ns) intervals of compute
    activity from a Perfetto doc: the r15 ``device:*`` stamp-buffer
    COMPUTE slices (``device_phase`` = reduce — the xfer slices are
    the collective's own communication and must never count as cover
    it hides behind) plus host-marked ``window:*`` compute spans."""
    per: dict = {}
    if not trace_doc:
        return per
    for ev in trace_doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        is_device_compute = args.get("device_track") \
            and args.get("device_phase") != "xfer"
        if not (is_device_compute
                or str(ev.get("name", "")).startswith("window:")):
            continue
        t0 = ev.get("ts", 0) * 1e3
        t1 = t0 + ev.get("dur", 0) * 1e3
        if t1 > t0:
            per.setdefault(ev.get("pid", -1), []).append((t0, t1))
    # merge overlapping/adjacent intervals per rank: _overlap_ns sums
    # the wire interval's intersection with EACH window, so unmerged
    # overlap (a host window: span containing device stamp slices)
    # would double-count cover and let recovered_compute exceed 1.0
    for r, wins in per.items():
        wins.sort()
        merged = [wins[0]]
        for w0, w1 in wins[1:]:
            if w0 <= merged[-1][1]:
                if w1 > merged[-1][1]:
                    merged[-1] = (merged[-1][0], w1)
            else:
                merged.append((w0, w1))
        per[r] = merged
    return per


def _overlap_ns(t0: float, t1: float, windows: list) -> float:
    """Total intersection of [t0, t1) with a sorted interval list."""
    total = 0.0
    for w0, w1 in windows:
        if w0 >= t1:
            break
        lo, hi = max(t0, w0), min(t1, w1)
        if hi > lo:
            total += hi - lo
    return total


def overlap(dumps, trace_doc: Optional[dict] = None) -> dict:
    """Wire-exposed vs compute-overlapped time per collective — the
    overlap accountant (precursor metric for ROADMAP item 3's
    device-initiated fusion: ACCL+ reports overlap as recovered
    compute fraction, arxiv 2312.11742).

    Per gang-instance member, the WIRE interval is everything after the
    gang assembled (dispatch → completion).  The part of it intersecting
    a compute window on the same rank (device stamp tracks, host
    ``window:`` spans) is *overlapped* — communication the rank hid
    behind compute; the rest is *exposed* — wall time the wire alone
    cost.  Fusion work shrinks ``exposed_fraction`` toward zero;
    ``recovered_compute_fraction`` is how much of the wire time compute
    already covers.

    Returns ``{"nranks", "compute_windows", "collectives": {key: {
    "wire_us", "overlapped_us", "exposed_us", "exposed_fraction",
    "recovered_compute_fraction", "episodes"}}}``."""
    doc = _ensure_merged(dumps)
    ranks = sorted(rd["rank"] for rd in doc["ranks"])
    instances = _gang_instances(doc)
    windows = _compute_windows(trace_doc)

    groups: dict = {}
    for key, members in sorted(instances.items()):
        comm, coll, tag, count, dtype, occ = key
        if len(members) < 2:
            continue
        nbytes = max(rec.get("nbytes", 0) for rec in members.values())
        gkey = f"{coll}|comm{comm}|{size_bucket(nbytes)}"
        g = groups.setdefault(gkey, {
            "collective": coll, "comm": comm,
            "size_bucket": size_bucket(nbytes), "episodes": 0,
            "wire_ns": 0.0, "overlapped_ns": 0.0, "span_ns": 0.0})
        g["episodes"] += 1
        for r, rec in members.items():
            t_sub = rec.get("t_submit") or 0
            t_cmp = rec.get("t_complete") or 0
            if not t_sub or not t_cmp or t_cmp <= t_sub:
                continue
            # wire = after the gang assembled: dispatch (or the best
            # earlier stamp) to completion — matches attribute()'s
            # wire+reduce tail
            t_wire = rec.get("t_dispatch") or rec.get("t_gang_ready") \
                or rec.get("t_queue") or t_sub
            t_wire = min(max(int(t_wire), t_sub), t_cmp)
            wire = t_cmp - t_wire
            g["span_ns"] += t_cmp - t_sub
            g["wire_ns"] += wire
            g["overlapped_ns"] += _overlap_ns(t_wire, t_cmp,
                                              windows.get(r, []))

    collectives: dict = {}
    for gkey, g in sorted(groups.items()):
        wire, ovl, span = g["wire_ns"], g["overlapped_ns"], g["span_ns"]
        exposed = max(wire - ovl, 0.0)
        collectives[gkey] = {
            "collective": g["collective"], "comm": g["comm"],
            "size_bucket": g["size_bucket"], "episodes": g["episodes"],
            "wire_us": round(wire / 1e3, 2),
            "overlapped_us": round(ovl / 1e3, 2),
            "exposed_us": round(exposed / 1e3, 2),
            # exposed wire as a fraction of total span: the wall-clock
            # share the wire alone cost (drops when a slow peer heals
            # OR when fusion hides the wire behind compute)
            "exposed_fraction": round(exposed / span, 4) if span else 0.0,
            "recovered_compute_fraction": round(ovl / wire, 4)
            if wire else 0.0,
        }
    return {
        "nranks": len(ranks),
        "compute_windows": sum(len(v) for v in windows.values()),
        "collectives": collectives,
    }


def device_overlap(trace_doc: Optional[dict]) -> dict:
    """Overlap accounting from the DEVICE stamp timeline alone (r18).

    For every ``device:<collective>`` track in a Perfetto doc, measure
    how much of the transfer (``device_phase`` = xfer) time runs
    concurrently with reduce/compute slices on the *same rank and
    collective* — the device-side twin of :func:`overlap`, which
    accounts host flight records against host compute windows.

    The sequential ring's stamp clock serializes every step
    (xfer [3s, 3s+1] then reduce [3s+1, 3s+2]) so its xfer∩reduce is
    zero and ``exposed_fraction`` is 1.0.  The fused lanes stamp the
    overlapped clock — chunk k+1's xfer spans chunk k's reduce — so
    all but the first transfer are covered and the exposed fraction
    falls to ~1/slots.  ``recovered_mxu_fraction`` is the share of
    wire time the MXU (reduce/compute phase) already hides.

    Returns::

        {"tracks": N,
         "collectives": {"<coll>": {"xfer_us", "overlapped_us",
             "exposed_us", "exposed_fraction",
             "recovered_mxu_fraction", "slices", "ranks"}}}
    """
    if not trace_doc:
        return {"tracks": 0, "collectives": {}}
    # (pid, tid) -> track label, from the thread_name metadata events
    labels: dict = {}
    for ev in trace_doc.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            labels[(ev.get("pid"), ev.get("tid"))] = \
                (ev.get("args") or {}).get("name", "")

    xfers: dict = {}    # (coll, pid) -> [(t0_ns, t1_ns)]
    reduces: dict = {}
    for ev in trace_doc.get("traceEvents", []):
        args = ev.get("args") or {}
        if ev.get("ph") != "X" or not args.get("device_track"):
            continue
        label = labels.get((ev.get("pid"), ev.get("tid")), "")
        coll = label[len("device:"):] if label.startswith("device:") \
            else label
        t0 = ev.get("ts", 0) * 1e3
        t1 = t0 + ev.get("dur", 0) * 1e3
        if t1 <= t0:
            continue
        key = (coll, ev.get("pid", -1))
        if args.get("device_phase") == "xfer":
            xfers.setdefault(key, []).append((t0, t1))
        else:
            reduces.setdefault(key, []).append((t0, t1))

    def _merge(wins: list) -> list:
        wins = sorted(wins)
        out = [wins[0]]
        for w0, w1 in wins[1:]:
            if w0 <= out[-1][1]:
                if w1 > out[-1][1]:
                    out[-1] = (out[-1][0], w1)
            else:
                out.append((w0, w1))
        return out

    agg: dict = {}
    for (coll, pid), xs in sorted(xfers.items()):
        cover = _merge(reduces.get((coll, pid), [])) \
            if (coll, pid) in reduces else []
        a = agg.setdefault(coll, {"xfer_ns": 0.0, "ovl_ns": 0.0,
                                  "slices": 0, "ranks": set()})
        a["ranks"].add(pid)
        for t0, t1 in xs:
            a["slices"] += 1
            a["xfer_ns"] += t1 - t0
            a["ovl_ns"] += _overlap_ns(t0, t1, cover)

    collectives: dict = {}
    for coll, a in sorted(agg.items()):
        xfer, ovl = a["xfer_ns"], a["ovl_ns"]
        exposed = max(xfer - ovl, 0.0)
        collectives[coll] = {
            "xfer_us": round(xfer / 1e3, 2),
            "overlapped_us": round(ovl / 1e3, 2),
            "exposed_us": round(exposed / 1e3, 2),
            "exposed_fraction": round(exposed / xfer, 4) if xfer else 0.0,
            "recovered_mxu_fraction": round(ovl / xfer, 4) if xfer
            else 0.0,
            "slices": a["slices"],
            "ranks": len(a["ranks"]),
        }
    return {"tracks": len({k for k in xfers} | {k for k in reduces}),
            "collectives": collectives}


def render(report: dict, out=None) -> str:
    """Human rendering of an attribution report (perf_doctor's body)."""
    lines = [
        f"critical-path attribution: {report['nranks']} rank(s), "
        f"{report['gangs_analyzed']} gang instance(s) analyzed",
        "  clock skew vs rank "
        f"{report['reference_rank']} (ns): {report['clock_skew_ns']}",
    ]
    for key, c in sorted(report["collectives"].items()):
        lines.append(
            f"\n{c['collective']} comm {c['comm']} {c['size_bucket']}: "
            f"{c['episodes']} episode(s), mean span "
            f"{c['span_us']:.1f}us (phase coverage "
            f"{c['phase_coverage'] * 100:.1f}%)")
        ph = c["phases_us"]
        span = max(c["span_us"], 1e-9)
        lines.append("  " + "  ".join(
            f"{p}={ph[p]:.1f}us ({ph[p] / span * 100:.0f}%)"
            for p in PHASES if ph.get(p)))
        for r, st in c["stragglers"].items():
            lines.append(
                f"  straggler rank {r}: {st['episodes']} episode(s) "
                f"({st['share'] * 100:.0f}%), mean late "
                f"{st['mean_late_us']:.1f}us, max {st['max_late_us']:.1f}us")
        d = c["dominant_straggler"]
        if d is not None and d["share"] >= 0.5:
            lines.append(
                f"  DOMINANT straggler: rank {d['rank']} arrives last "
                f"in {d['share'] * 100:.0f}% of late episodes "
                f"(mean +{d['mean_late_us']:.1f}us)")
    text = "\n".join(lines) + "\n"
    if out is not None:
        out.write(text)
    return text
