"""Async request layer.

Equivalent of the reference request objects: every collective call returns
a handle the user can wait on (with optional timeout); completion carries
the engine retcode and a duration read from the performance counter
(reference: driver/xrt/include/accl/acclrequest.hpp:39-211 BaseRequest /
FPGAQueue; driver/xrt/src/fpgadevice.cpp:24-33 finish_fpga_request).

Per-device call serialization (the reference's FPGAQueue) is preserved:
backends push requests through a `RequestQueue` so only one call is
outstanding per engine command stream at a time, while the engine itself
may interleave retried rendezvous calls internally.
"""
from __future__ import annotations

import itertools
import os
import threading
from typing import Callable, Optional

from .constants import ACCLError, OperationStatus, error_code_to_str
from .observability import flight as _flight
from .observability import trace as _trace

#: sentinel distinguishing "no timeout passed" (resolve the configurable
#: default) from an explicit None (block forever, the pre-r8 behavior)
_WAIT_DEFAULT = object()


def default_wait_timeout_s() -> float:
    """Default Request.wait budget in seconds, derived from the same
    ``ACCL_DEFAULT_TIMEOUT`` knob as the engine receive budget (µs,
    accl.default_timeout) plus generous host headroom — the driver wait
    must always fire AFTER the engine's own timeout so a stall surfaces
    as a decodable retcode first, and a bare ``wait()`` can no longer
    hang a production process forever."""
    raw = os.environ.get("ACCL_DEFAULT_TIMEOUT", "1000000")
    try:
        engine_s = float(raw) / 1e6
    except ValueError:
        engine_s = 1.0
    return engine_s + 59.0


class Request:
    """Handle for one in-flight collective call."""

    _ids = itertools.count()

    #: persistent-plan auto-capture lane (accl_tpu/plans.py,
    #: ACCL_PLAN_AUTO): class-level defaults so the per-call hot path
    #: pays ZERO extra attribute writes — the driver sets an instance
    #: `plan_intent` only on streak calls, and the engine publishes the
    #: armed ring as an instance `plan_ring` only on the one gang
    #: instance where every member agreed.
    plan_intent = False
    plan_ring = None

    def __init__(self, description: str = "", sync: bool = False):
        self.id = next(Request._ids)
        self.description = description
        #: True when the submitter will BLOCK on this request (no
        #: run_async): the backend may then complete the call inline on
        #: the submitting thread (leader dispatch) instead of handing it
        #: to an executor — the submitter cannot issue another call
        #: until this one completes, so inline execution costs it
        #: nothing and saves the executor hop.  False (default) keeps
        #: the posted-descriptor path: the submitter wants its thread
        #: back immediately.
        self.sync = sync
        self.status = OperationStatus.QUEUED
        self.retcode: int = 0
        self.duration_ns: float = 0.0
        self._done = threading.Event()
        #: optional callback run on completion (used by the driver to sync
        #: result buffers back to the host, mirroring the async completion
        #: thread of the reference backend).
        self.on_complete: Optional[Callable[["Request"], None]] = None
        #: optional thunk run ONCE at the top of wait(), on the waiting
        #: thread, before blocking.  Backends use it to defer leader-
        #: dispatch work out of the submission path: submit() runs under
        #: the rank's RequestQueue lock, and executing a gang program
        #: there would stall concurrent submissions on the same handle —
        #: wait() runs after submit returns, lock released.
        self.pre_wait: Optional[Callable[[], None]] = None
        #: exception raised by on_complete, surfaced via check()
        self.callback_error: Optional[Exception] = None
        #: observability (accl_tpu/observability): `trace` is this
        #: call's TraceSpan (None when tracing is off — the
        #: zero-allocation fast path), `metric` is the driver-attached
        #: (registry, collective, dtype, nbytes, nranks, t_submit_ns,
        #: tenant) tuple published at completion.  Both set by
        #: ACCL._execute.
        self.trace: Optional[object] = None
        self.metric: Optional[tuple] = None
        #: always-on flight-recorder record (observability/flight.py);
        #: None only when ACCL_FLIGHT=0 or the request predates
        #: initialize.  Set by ACCL._observe_call; state transitions are
        #: stamped in place by the queue and the backends.
        self.flight: Optional[_flight.FlightRecord] = None
        #: True once a wait() observed completion — the signal the
        #: collective sanitizer's leaked-request checker and
        #: ACCL.deinit() use to tell a drained async call from one
        #: whose completion (and retcode) nobody ever looked at
        self.waited = False

    def complete(self, retcode: int, duration_ns: float = 0.0) -> None:
        self.retcode = retcode
        self.duration_ns = duration_ns
        self.status = OperationStatus.COMPLETED
        try:
            if self.on_complete is not None:
                self.on_complete(self)
        except Exception as e:  # surface via check(), never lose the event
            self.callback_error = e
        finally:
            self._observe()
            self._done.set()

    def _observe(self) -> None:
        """Publish this call's completion to the observability layer:
        callback-complete timestamp on the span (the last event — the
        result-buffer sync in on_complete has already run), metrics
        observation keyed by the driver-attached signature.  Observer
        failures must never lose the completion event."""
        if self.metric is None and self.trace is None \
                and self.flight is None:
            return
        try:
            t_end = _trace.now_ns()
            if self.flight is not None:
                self.flight.finish(self.retcode, t_end)
            if self.metric is not None:
                reg, coll, dtype, nbytes, nranks, t0, tenant = self.metric
                reg.observe_call(coll, dtype, nbytes, t_end - t0, nranks,
                                 ok=self.retcode == 0,
                                 engine_ns=self.duration_ns,
                                 tenant=tenant)
            span = self.trace
            if span is not None:
                span.t_complete = t_end
                _trace.collector().add(span)
        except Exception:  # pragma: no cover — observability is best-effort
            pass

    def wait(self, timeout=_WAIT_DEFAULT) -> bool:
        """Block until completion; returns False on timeout
        (reference: cclo.hpp:149-150 wait w/ timeout).

        A bare ``wait()`` uses the configurable default budget
        (:func:`default_wait_timeout_s`, driven by ACCL_DEFAULT_TIMEOUT)
        instead of blocking forever; pass ``timeout=None`` explicitly
        for an unbounded wait."""
        if timeout is _WAIT_DEFAULT:
            timeout = default_wait_timeout_s()
        thunk, self.pre_wait = self.pre_wait, None
        if thunk is not None:
            thunk()
        ok = self._done.wait(timeout)
        if ok:
            self.waited = True
        return ok

    def flight_info(self) -> str:
        """The flight-recorder view of this call, for error embedding
        ('' when the recorder is off): seq, state, lane, age."""
        rec = self.flight
        if rec is None:
            return ""
        return f" [flight: {rec.summary()}]"

    def check(self) -> None:
        """Raise if the engine reported a non-zero retcode, the
        completion callback failed, or — called after a wait() timeout —
        the call is still in flight, with the flight-recorder record
        (seq, state, lane, age) embedded so a timeout is diagnosable
        from the exception alone
        (reference: accl.cpp:1226-1250 check_return_value)."""
        if self.done:
            # checking a completed request IS observing its outcome:
            # poll-then-check drains a call as thoroughly as wait(), so
            # the sanitizer's leaked-request checker must not flag it
            self.waited = True
        if not self.done:
            raise ACCLError(
                f"{self.description or 'call'} timed out: request id "
                f"{self.id} still in flight"
                f" (status={self.status.name}){self.flight_info()}")
        if self.retcode != 0:
            raise ACCLError(
                f"{self.description or 'call'} failed: "
                f"{error_code_to_str(self.retcode)}{self.flight_info()}",
                self.retcode,
            )
        if self.callback_error is not None:
            raise ACCLError(
                f"{self.description or 'call'} completion failed: "
                f"{self.callback_error}"
            ) from self.callback_error

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def aborted(self) -> bool:
        """True when this call was finalized by a communicator abort
        (COMM_ABORTED) rather than completing or failing on its own —
        the signal recovery code branches on (shrink + re-run) without
        string-matching the error text."""
        from .constants import ErrorCode

        return self.done and bool(self.retcode & int(ErrorCode.COMM_ABORTED))

    def __repr__(self) -> str:
        return f"Request(id={self.id}, {self.description!r}, status={self.status.name})"


class RequestQueue:
    """Serializes the *submission* of calls onto a device command stream
    (the reference FPGAQueue's enqueue step, acclrequest.hpp:153-211).
    Engines accept multiple outstanding calls — retried rendezvous calls
    interleave by design — so completion ordering is backend territory;
    only the descriptor push is atomic here."""

    def __init__(self):
        self._lock = threading.Lock()

    def submit(self, request: Request, start_fn: Callable[[Request], None]) -> Request:
        with self._lock:
            request.status = OperationStatus.EXECUTING
            rec = request.flight
            if rec is not None:
                rec.t_queue = _trace.now_ns()
                rec.state = _flight.S_QUEUED
            if request.trace is not None:
                request.trace.t_queue = _trace.now_ns()
            start_fn(request)
        return request
