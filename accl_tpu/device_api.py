"""Device-side caller API — parity with the reference HLS bindings.

The reference lets FPGA PL kernels issue collectives *without the host
driver*: ``accl_hls::ACCLCommand`` (driver/hls/accl_hls.h:82-500) streams
the 15-word call descriptor to the client_arbiter and blocks on the ack
stream, while ``accl_hls::ACCLData`` (accl_hls.h:502-543) pushes/pulls
512-bit data words on the CCLO's kernel stream ports.  ``vadd_put``
(kernels/plugins/vadd_put/vadd_put.cpp:23-86) is the canonical user.

Two call sites exist on the TPU build:

1. **Kernel-initiated calls against the engine backend** — the classes
   below.  `ACCLCommand` posts raw descriptors straight onto the engine's
   command queue (the client_arbiter role: the queue accepts call bundles
   from any thread, host or kernel), and `ACCLData` wraps the kernel
   stream push/pop.  This is the rung the reference exercises in
   test/host/hls/test.cpp with CCLO_BFM.
2. **In-jit device code** — XLA is the arbiter there; `DeviceCollectives`
   binds the SPMD lowerings (accl_tpu.parallel.collectives) to one mesh
   axis under the same method names, so device-side code is written
   against the same surface either way.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .accl import GLOBAL_COMM
from .backends.base import CCLODevice
from .constants import (
    TAG_ANY,
    CCLOCall,
    CompressionFlags,
    HostFlags,
    Operation,
    OperationStatus,
    StreamFlags,
)
from .request import Request


def _collectives():
    from .parallel import collectives
    return collectives


class ACCLCommand:
    """Issue raw call descriptors from kernel code.

    Mirrors ``accl_hls::ACCLCommand``: the constructor captures the
    communicator and datapath-config ids (accl_hls.h:84-107), each helper
    marshals one descriptor (:219-500), and ``finalize_call`` blocks on
    the ack (:204-216).  Buffer operands are raw device addresses, as on
    the reference's command stream — kernels do not hold driver buffer
    objects.
    """

    def __init__(self, device: CCLODevice, comm: int = GLOBAL_COMM,
                 arithcfg: int = 0):
        self._device = device
        self._comm = comm
        self._arithcfg = arithcfg
        self._pending: Optional[Request] = None

    # -- raw descriptor path (accl_hls.h:134-216) ----------------------
    def start_call(self, scenario: Operation, count: int,
                   root_src_dst: int = 0, function: int = 0,
                   tag: int = TAG_ANY,
                   compression_flags: CompressionFlags =
                   CompressionFlags.NO_COMPRESSION,
                   stream_flags: StreamFlags = StreamFlags.NO_STREAM,
                   addr_0: int = 0, addr_1: int = 0,
                   addr_2: int = 0) -> Request:
        """Post one 15-word descriptor on the engine command queue and
        return the pending request (the ack stream handle)."""
        if self._pending is not None:
            raise RuntimeError(
                "previous call not finalized (the reference command stream "
                "is strictly call/ack ordered per client)")
        call = CCLOCall(
            scenario=scenario, count=count, comm=self._comm,
            root_src_dst=root_src_dst, function=function, tag=tag,
            arithcfg=self._arithcfg, compression_flags=compression_flags,
            stream_flags=stream_flags, host_flags=HostFlags.NO_HOST,
            addr_0=addr_0, addr_1=addr_1, addr_2=addr_2,
        )
        req = Request(f"krnl:{scenario.name}")
        req.status = OperationStatus.EXECUTING
        self._device.start(call, req)
        self._pending = req
        return req

    def finalize_call(self, timeout: float = 60.0) -> int:
        """Block until the engine acks the call; raises on a non-zero
        retcode (accl_hls.h:204-216 reads the sts stream).  On timeout the
        call stays pending — it is still in flight on the engine, so the
        client must not issue another descriptor."""
        req = self._pending
        if req is None:
            raise RuntimeError("no call in flight")
        if not req.wait(timeout=timeout):
            raise TimeoutError("kernel call not acked")
        self._pending = None
        req.check()
        return req.retcode

    def _run(self, *args, **kw) -> int:
        self.start_call(*args, **kw)
        return self.finalize_call()

    # -- per-collective helpers (accl_hls.h:219-500) --------------------
    def copy(self, count: int, src_addr: int, dst_addr: int) -> int:
        return self._run(Operation.copy, count, addr_0=src_addr,
                         addr_2=dst_addr)

    def combine(self, count: int, function: int, op0_addr: int,
                op1_addr: int, res_addr: int) -> int:
        return self._run(Operation.combine, count, function=function,
                         addr_0=op0_addr, addr_1=op1_addr, addr_2=res_addr)

    def send(self, count: int, tag: int, dst: int,
             src_addr: int = 0,
             stream_flags: StreamFlags = StreamFlags.NO_STREAM) -> int:
        return self._run(Operation.send, count, root_src_dst=dst, tag=tag,
                         addr_0=src_addr, stream_flags=stream_flags)

    def recv(self, count: int, tag: int, src: int,
             dst_addr: int = 0,
             stream_flags: StreamFlags = StreamFlags.NO_STREAM) -> int:
        return self._run(Operation.recv, count, root_src_dst=src, tag=tag,
                         addr_2=dst_addr, stream_flags=stream_flags)

    def stream_put(self, count: int, stream_id: int, dst: int,
                   src_addr: int = 0,
                   from_stream: bool = True) -> int:
        """Put into a remote kernel stream (accl_hls.h:277-298).  With
        ``from_stream`` the payload comes off the local kernel input
        stream (the vadd_put pattern); otherwise from ``src_addr``."""
        if stream_id < 9:
            raise ValueError("stream ids < 9 are reserved")
        flags = StreamFlags.RES_STREAM
        if from_stream:
            flags |= StreamFlags.OP0_STREAM
        return self._run(Operation.send, count, root_src_dst=dst,
                         tag=stream_id, addr_0=src_addr, stream_flags=flags)

    def bcast(self, count: int, root: int, addr: int) -> int:
        return self._run(Operation.bcast, count, root_src_dst=root,
                         addr_0=addr, addr_2=addr)

    def scatter(self, count: int, root: int, src_addr: int,
                dst_addr: int) -> int:
        return self._run(Operation.scatter, count, root_src_dst=root,
                         addr_0=src_addr, addr_2=dst_addr)

    def gather(self, count: int, root: int, src_addr: int,
               dst_addr: int) -> int:
        return self._run(Operation.gather, count, root_src_dst=root,
                         addr_0=src_addr, addr_2=dst_addr)

    def reduce(self, count: int, root: int, function: int, src_addr: int,
               dst_addr: int) -> int:
        return self._run(Operation.reduce, count, root_src_dst=root,
                         function=function, addr_0=src_addr,
                         addr_2=dst_addr)

    def allgather(self, count: int, src_addr: int, dst_addr: int) -> int:
        return self._run(Operation.allgather, count, addr_0=src_addr,
                         addr_2=dst_addr)

    def allreduce(self, count: int, function: int, src_addr: int,
                  dst_addr: int) -> int:
        return self._run(Operation.allreduce, count, function=function,
                         addr_0=src_addr, addr_2=dst_addr)

    def reduce_scatter(self, count: int, function: int, src_addr: int,
                       dst_addr: int) -> int:
        return self._run(Operation.reduce_scatter, count, function=function,
                         addr_0=src_addr, addr_2=dst_addr)

    def barrier(self) -> int:
        return self._run(Operation.barrier, 0)


class ACCLData:
    """Kernel data streams (``accl_hls::ACCLData``, accl_hls.h:502-543):
    push operand bytes into the engine's kernel input stream and pull
    results from a named output stream."""

    def __init__(self, device: CCLODevice):
        self._device = device

    def push(self, data: np.ndarray) -> None:
        """Stream operand words to the engine (data_to_cclo port)."""
        self._device.push_krnl(np.asarray(data))

    def pull(self, count: int, dtype=np.float32, stream_id: int = 9,
             timeout: float = 10.0) -> np.ndarray:
        """Pull one message from a kernel output stream
        (data_from_cclo port, routed by the wire header's strm field)."""
        nbytes = count * np.dtype(dtype).itemsize
        raw = self._device.pop_stream(stream_id, nbytes, timeout)
        if raw is None:
            raise TimeoutError(f"no message on stream {stream_id}")
        if len(raw) != nbytes:
            raise ValueError(
                f"stream {stream_id} message is {len(raw)} bytes, "
                f"expected {nbytes} ({count} x {np.dtype(dtype).name})")
        return np.frombuffer(raw, dtype=dtype).copy()


class DeviceCollectives:
    """The in-jit half: same helper names, bound to one mesh axis.

    Inside ``shard_map``/``pjit``-traced code XLA plays the arbiter and
    scheduler, so each method is just the SPMD lowering from
    accl_tpu.parallel.collectives pinned to this instance's axis."""

    def __init__(self, axis: str = "rank"):
        self.axis = axis

    def allreduce(self, x, op: str = "sum"):
        return _collectives().all_reduce(x, self.axis, op)

    def reduce(self, x, root: int, op: str = "sum"):
        return _collectives().reduce(x, root, self.axis, op)

    def allgather(self, x, tiled: bool = True):
        return _collectives().all_gather(x, self.axis, tiled=tiled)

    def reduce_scatter(self, x):
        return _collectives().reduce_scatter(x, self.axis)

    def alltoall(self, x, split_axis: int = 0, concat_axis: int = 0):
        return _collectives().all_to_all(x, self.axis, split_axis,
                                         concat_axis)

    def bcast(self, x, root: int):
        return _collectives().broadcast(x, root, self.axis)

    def scatter(self, x, root: int):
        return _collectives().scatter(x, root, self.axis)

    def gather(self, x, root: int):
        return _collectives().gather(x, root, self.axis)

    def send_recv(self, x, src: int, dst: int):
        return _collectives().send_recv(x, src, dst, self.axis)

    def barrier(self):
        return _collectives().barrier(self.axis)
