"""Device-mesh construction helpers.

The reference binds ranks to network endpoints (ip/port/session tables,
accl_network_utils.cpp:264-289 generate_ranks); the TPU equivalent binds
logical parallelism axes to the physical ICI topology via
`jax.sharding.Mesh`.  Axis conventions used across the framework:

- ``dp``: data parallel (gradient all-reduce / ZeRO reduce-scatter)
- ``fsdp``: fully-sharded data parallel (param all-gather axis)
- ``tp``: tensor parallel (matmul-sharded all-reduce/all-gather)
- ``sp``: sequence/context parallel (ring attention / Ulysses all-to-all)
- ``pp``: pipeline parallel (stage-to-stage send/recv)
- ``ep``: expert parallel (MoE all-to-all)
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


@dataclass
class MeshConfig:
    """Logical axis sizes; unspecified axes default to 1 and axes of size
    1 are dropped from the mesh."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    def axes(self) -> dict[str, int]:
        return {k: v for k, v in vars(self).items() if v > 1}

    @property
    def num_devices(self) -> int:
        n = 1
        for v in vars(self).values():
            n *= v
        return n


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None,
              **axis_sizes) -> "object":
    """Build a Mesh with the requested logical axes.

    `make_mesh(dp=2, tp=4)` on 8 devices → Mesh with axes ("dp", "tp").
    Axis order follows the declaration order of MeshConfig, which places
    the fastest-communicating axes (tp/sp) innermost so they map onto
    contiguous ICI neighbors ("How to Scale Your Model" recipe: pick a
    mesh, let XLA insert collectives along it).
    """
    import jax
    from jax.sharding import Mesh

    if config is None:
        config = MeshConfig(**axis_sizes)
    axes = config.axes()
    if not axes:
        axes = {"dp": 1}
    devs = list(devices) if devices is not None else jax.devices()
    need = int(np.prod(list(axes.values())))
    if len(devs) < need:
        raise ValueError(f"mesh needs {need} devices, have {len(devs)}")
    grid = np.array(devs[:need]).reshape(tuple(axes.values()))
    return Mesh(grid, tuple(axes.keys()))


def make_hybrid_mesh(ici: dict, dcn: dict, devices=None) -> "object":
    """Multi-slice mesh: `dcn` axes span slices over the data-center
    network, `ici` axes stay within a slice.

    The reference reaches multi-node scale by putting its POEs on the
    machine-room Ethernet (SURVEY §5 "distributed communication
    backend"); the TPU equivalent is a hybrid mesh where slow
    (DCN-crossing) axes are outermost and fast ICI axes innermost, so
    XLA's collectives ride ICI unless an axis genuinely spans slices.

    On real multi-slice hardware this defers to
    `mesh_utils.create_hybrid_device_mesh` (which groups devices by
    slice_index); on a single slice — or the CPU test platform — devices
    are blocked row-major, DCN axes slowest-varying, which preserves the
    same axis semantics for compile-level validation.
    """
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    dcn_sizes = {k: v for k, v in dcn.items() if v > 1}
    ici_sizes = {k: v for k, v in ici.items() if v > 1}
    names = tuple(dcn_sizes) + tuple(ici_sizes)
    shape = tuple(dcn_sizes.values()) + tuple(ici_sizes.values())
    need = int(np.prod(shape)) if shape else 1
    if len(devs) < need:
        raise ValueError(f"hybrid mesh needs {need} devices, have {len(devs)}")

    slice_ids = sorted({getattr(d, "slice_index", 0) for d in devs})
    if len(slice_ids) > 1:
        # real multi-slice: dcn axes index slice groups, ici axes stay
        # inside one slice — built directly so the invariant holds for
        # any number of axes per level
        n_dcn = int(np.prod(tuple(dcn_sizes.values()))) if dcn_sizes else 1
        n_ici = int(np.prod(tuple(ici_sizes.values()))) if ici_sizes else 1
        if len(slice_ids) != n_dcn:
            raise ValueError(
                f"dcn axes size {n_dcn} != visible slices {len(slice_ids)}")
        groups = {s: [d for d in devs if getattr(d, "slice_index", 0) == s]
                  for s in slice_ids}
        short = [s for s in slice_ids if len(groups[s]) < n_ici]
        if short:
            raise ValueError(
                f"ici axes need {n_ici} devices per slice; slices {short} "
                f"have fewer")
        grid = np.array([groups[s][:n_ici] for s in slice_ids]).reshape(shape)
    else:
        grid = np.array(devs[:need]).reshape(shape)
    return Mesh(grid, names)
