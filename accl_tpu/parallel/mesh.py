"""Device-mesh construction helpers.

The reference binds ranks to network endpoints (ip/port/session tables,
accl_network_utils.cpp:264-289 generate_ranks); the TPU equivalent binds
logical parallelism axes to the physical ICI topology via
`jax.sharding.Mesh`.  Axis conventions used across the framework:

- ``dp``: data parallel (gradient all-reduce / ZeRO reduce-scatter)
- ``fsdp``: fully-sharded data parallel (param all-gather axis)
- ``tp``: tensor parallel (matmul-sharded all-reduce/all-gather)
- ``sp``: sequence/context parallel (ring attention / Ulysses all-to-all)
- ``pp``: pipeline parallel (stage-to-stage send/recv)
- ``ep``: expert parallel (MoE all-to-all)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np


@dataclass
class MeshConfig:
    """Logical axis sizes; unspecified axes default to 1 and axes of size
    1 are dropped from the mesh."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    def axes(self) -> dict[str, int]:
        return {k: v for k, v in vars(self).items() if v > 1}

    @property
    def num_devices(self) -> int:
        n = 1
        for v in vars(self).values():
            n *= v
        return n


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None,
              **axis_sizes) -> "object":
    """Build a Mesh with the requested logical axes.

    `make_mesh(dp=2, tp=4)` on 8 devices → Mesh with axes ("dp", "tp").
    Axis order follows the declaration order of MeshConfig, which places
    the fastest-communicating axes (tp/sp) innermost so they map onto
    contiguous ICI neighbors ("How to Scale Your Model" recipe: pick a
    mesh, let XLA insert collectives along it).
    """
    import jax
    from jax.sharding import Mesh

    if config is None:
        config = MeshConfig(**axis_sizes)
    axes = config.axes()
    if not axes:
        axes = {"dp": 1}
    devs = list(devices) if devices is not None else jax.devices()
    need = int(np.prod(list(axes.values())))
    if len(devs) < need:
        raise ValueError(f"mesh needs {need} devices, have {len(devs)}")
    grid = np.array(devs[:need]).reshape(tuple(axes.values()))
    return Mesh(grid, tuple(axes.keys()))
