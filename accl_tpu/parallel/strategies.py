"""Parallelism strategies built on the collective layer.

SURVEY §2.8 maps the reference's collectives onto the ML-parallelism
vocabulary; this module provides each strategy as a composable function
meant to run inside shard_map/pjit over the mesh axes from
:mod:`accl_tpu.parallel.mesh`:

- data parallel        ← allreduce          (fw :1855-2075)
- ZeRO/FSDP            ← reduce_scatter + all_gather (fw :1748, :1299)
- tensor parallel      ← psum / all_gather  (fw :1855, :1299)
- pipeline parallel    ← tagged send/recv shifts (fw :575-712; async
                         requests + multi-communicator in the driver)
- expert parallel      ← all_to_all         (fw :2123-2218)
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size as _axis_size


# ---------------------------------------------------------------------------
# data parallel
# ---------------------------------------------------------------------------
def _pad_to_multiple(flat, size: int):
    """Zero-pad a flat array so its length divides `size`."""
    pad = (-flat.shape[0]) % size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, flat.dtype)])
    return flat


def sync_gradients(grads, axis: str = "dp", compress: Optional[str] = None,
                   mean: bool = True, error_feedback: bool = False,
                   stochastic: bool = False, seed: int = 0):
    """All-reduce a gradient pytree across the data-parallel axis.

    `compress="bf16"|"f16"` models the reference's on-the-wire fp16
    compression (ETH_COMPRESSED) for gradient sync: payloads cross the
    link in half precision, accumulate in fp32.  `compress="int8"` goes
    one tier further than the reference's lane set: the leaf rides a
    quantized ring allreduce (int8 wire + per-block fp32 scales, 4:1 —
    ops/quantized.py).  `error_feedback`/`stochastic`/`seed` forward to
    the quantized ring's per-hop requantization error carry (EQuARX);
    they only apply to the int8 lane."""
    size = _axis_size(axis)

    def sync_leaf(g):
        orig = g.dtype
        if compress == "int8":
            from ..ops.quantized import quantized_all_reduce

            flat = _pad_to_multiple(g.astype(jnp.float32).reshape(-1), size)
            out = quantized_all_reduce(flat, axis,
                                       error_feedback=error_feedback,
                                       stochastic=stochastic, seed=seed)
            if mean:
                out = out / size
            n = g.size
            return out[:n].reshape(g.shape).astype(orig)
        if compress == "bf16":
            g = g.astype(jnp.bfloat16).astype(jnp.float32)
        elif compress == "f16":
            g = g.astype(jnp.float16).astype(jnp.float32)
        out = lax.pmean(g, axis) if mean else lax.psum(g, axis)
        return out.astype(orig)

    return jax.tree_util.tree_map(sync_leaf, grads)


def zero_shard_gradients(grads, axis: str = "dp"):
    """ZeRO-1 style: reduce-scatter each flat gradient so every member
    owns 1/P of the reduced values (optimizer-state sharding)."""
    size = _axis_size(axis)

    def shard_leaf(g):
        flat = _pad_to_multiple(g.reshape(-1), size)
        return lax.psum_scatter(flat, axis, scatter_dimension=0, tiled=True)

    return jax.tree_util.tree_map(shard_leaf, grads)


def zero_unshard_params(shards, shapes, axis: str = "dp"):
    """Inverse of :func:`zero_shard_gradients`: all-gather the owned
    shards back into full parameters (shapes: matching pytree of
    jnp.shape tuples)."""

    def gather_leaf(s, shape):
        full = lax.all_gather(s, axis, tiled=True)
        n = 1
        for d in shape:
            n *= d
        return full[:n].reshape(shape)

    return jax.tree_util.tree_map(gather_leaf, shards, shapes)


# ---------------------------------------------------------------------------
# tensor parallel
# ---------------------------------------------------------------------------
def column_parallel(x, w_shard, axis: str = "tp", gather_output: bool = False):
    """y_shard = x @ W[:, shard]; optionally all-gather the columns.
    (Megatron column-parallel linear; comm only if gather_output.)"""
    y = jnp.dot(x, w_shard, preferred_element_type=jnp.float32).astype(x.dtype)
    if gather_output:
        y = lax.all_gather(y, axis, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel(x_shard, w_shard, axis: str = "tp"):
    """y = sum_over_shards(x[shard] @ W[shard, :]) — the partial products
    all-reduce over the tp ring (the fused matmul+allreduce pattern)."""
    partial = jnp.dot(x_shard, w_shard,
                      preferred_element_type=jnp.float32)
    return lax.psum(partial, axis).astype(x_shard.dtype)


# ---------------------------------------------------------------------------
# pipeline parallel
# ---------------------------------------------------------------------------
def pipeline_apply(stage_fn: Callable, params, x_microbatches,
                   axis: str = "pp"):
    """GPipe-style pipeline over the `axis` ring.

    Every member holds one stage's `params`.  `x_microbatches`
    [M, ...batch...] enters stage 0; outputs [M, ...] emerge from the
    last stage (other members return zeros).  The schedule runs
    M + P - 1 ticks; activations shift stage→stage each tick via
    ppermute — the reference's tagged send/recv between pipeline
    neighbors (async requests + per-stage communicators in the driver).
    """
    P = _axis_size(axis)
    idx = lax.axis_index(axis)
    M = x_microbatches.shape[0]
    fwd = [(i, i + 1) for i in range(P - 1)]  # no wraparound

    def tick(carry, t):
        act = carry
        mb = jnp.clip(t, 0, M - 1)
        x_in = jnp.where(idx == 0,
                         x_microbatches[mb].astype(jnp.float32),
                         act)
        y = stage_fn(params, x_in)
        act_next = lax.ppermute(y, axis, fwd)
        # last stage's output for microbatch (t - (P-1)) appears at tick t
        return act_next, y

    # derive the initial carry from a real stage output so its
    # varying-axes type matches the loop body under shard_map (the
    # inputs may vary over other mesh axes besides `axis`)
    zeros = jnp.zeros_like(
        lax.ppermute(stage_fn(params, x_microbatches[0].astype(jnp.float32)),
                     axis, fwd))
    _, ys = lax.scan(tick, zeros, jnp.arange(M + P - 1))
    # member P-1 produced microbatch m at tick m + P - 1
    outs = ys[P - 1:P - 1 + M]
    return jnp.where(idx == P - 1, outs, jnp.zeros_like(outs))


# ---------------------------------------------------------------------------
# expert parallel (MoE)
# ---------------------------------------------------------------------------
def expert_dispatch(x, expert_idx, axis: str = "ep", capacity: int = 0):
    """Route tokens to the member hosting their expert via all-to-all
    (one expert per member).  x: [N, D], expert_idx: [N] in [0, P).
    Returns (expert_inputs [P*cap, D], combine_info) — dropped tokens
    (over capacity) combine to zero, mirroring standard MoE capacity
    semantics."""
    P = _axis_size(axis)
    N, D = x.shape
    cap = capacity or -(-N // P)
    # slot each token within its expert bucket
    onehot = jax.nn.one_hot(expert_idx, P, dtype=jnp.int32)  # [N, P]
    pos_in_expert = jnp.cumsum(onehot, axis=0) * onehot - 1  # [N, P]
    slot = jnp.sum(pos_in_expert * onehot, axis=1)  # [N]
    keep = slot < cap
    # buckets[e, c] = token index destined for expert e at slot c
    buckets = jnp.zeros((P, cap, D), x.dtype)
    buckets = buckets.at[expert_idx, jnp.clip(slot, 0, cap - 1)].add(
        jnp.where(keep[:, None], x, 0.0))
    # exchange buckets: member e receives every member's bucket e
    recv = lax.all_to_all(buckets, axis, split_axis=0, concat_axis=0,
                          tiled=False)  # [P, cap, D] from each source
    return recv.reshape(P * cap, D), (expert_idx, slot, keep, cap)


def expert_combine(y, combine_info, axis: str = "ep"):
    """Inverse of dispatch: return expert outputs to their source member
    and scatter back into token order.  y: [P*cap, D]."""
    P = _axis_size(axis)
    expert_idx, slot, keep, cap = combine_info
    D = y.shape[-1]
    back = lax.all_to_all(y.reshape(P, cap, D), axis, split_axis=0,
                          concat_axis=0, tiled=False)  # [P, cap, D]
    gathered = back[expert_idx, jnp.clip(slot, 0, cap - 1)]
    return jnp.where(keep[:, None], gathered, 0.0)
