"""Ring attention: sequence/context parallelism over the ICI ring.

SURVEY §5 notes the reference's ring schedules with fused
recv-reduce-send are "precisely ring attention's comm pattern"; this
module builds that pattern as a first-class feature.  Each member holds
a sequence shard of Q/K/V; K/V blocks rotate around the ring (ppermute —
the eager ring relay, fw :1404-1502) while a streaming-softmax
accumulator folds each arriving block into the local output — the
fused_recv_reduce of the firmware (fw :718) with the log-sum-exp
update playing the reduction operator.

Causal masking is blockwise: a K/V block strictly in the future
contributes nothing, the diagonal block takes a triangular mask, past
blocks attend fully.

Call inside shard_map with q/k/v sharded on the sequence axis:
    out = ring_attention(q, k, v, axis="sp", causal=True)
    q,k,v: [B, T_local, H, D] → out: [B, T_local, H, D]
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, bias):
    """Scores + masked streaming-softmax contributions for one K/V block.

    q: [B, Tq, H, D], k/v: [B, Tk, H, D], bias: [Tq, Tk] additive mask.
    Returns (m_blk [B,H,Tq], p [B,H,Tq,Tk], pv [B,Tq,H,D])."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    # [B, H, Tq, Tk]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = s + bias[None, None, :, :]
    m_blk = jnp.max(s, axis=-1)
    p = jnp.exp(s - m_blk[..., None])
    # zero fully-masked rows (m_blk == NEG_INF -> exp(0)=1 garbage)
    dead = m_blk <= NEG_INF / 2
    p = jnp.where(dead[..., None], 0.0, p)
    m_blk = jnp.where(dead, NEG_INF, m_blk)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    return m_blk, p, pv


def ring_attention(q, k, v, axis: str = "sp", causal: bool = False):
    """Exact attention over the full (ring-distributed) sequence.

    Per-member shapes [B, T_local, H, D]; the global sequence is the
    rank-major concatenation of shards.  Numerics accumulate in fp32
    regardless of input dtype.
    """
    P = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    perm = [(i, (i + 1) % P) for i in range(P)]

    qf = q.astype(jnp.float32)

    def step(s, carry):
        o, m, l, kc, vc = carry
        # current block originated at rank (idx - s) mod P
        src = (idx - s) % P
        if causal:
            qpos = idx * Tl + lax.broadcasted_iota(jnp.int32, (Tl, Tl), 0)
            kpos = src * Tl + lax.broadcasted_iota(jnp.int32, (Tl, Tl), 1)
            bias = jnp.where(qpos >= kpos, 0.0, NEG_INF).astype(jnp.float32)
        else:
            bias = jnp.zeros((Tl, Tl), jnp.float32)
        m_blk, p, pv = _block_attn(qf, kc.astype(jnp.float32),
                                   vc.astype(jnp.float32), bias)
        m_new = jnp.maximum(m, m_blk)
        # guard the all-dead case (exp(NEG_INF - NEG_INF) = 1)
        alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        beta = jnp.where(m_blk <= NEG_INF / 2, 0.0, jnp.exp(m_blk - m_new))
        l_new = l * alpha + jnp.sum(p, axis=-1) * beta
        o_new = (o * alpha.transpose(0, 2, 1)[..., None]
                 + pv * beta.transpose(0, 2, 1)[..., None])
        # rotate K/V one hop (the ring relay)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return o_new, m_new, l_new, kc, vc

    # accumulators must carry the same device-variance (vma) as the
    # values the loop produces — derive their zeros from q/k/v so the
    # fori_loop carry types match under any mesh composition
    zkv = (jnp.sum(k).astype(jnp.float32)
           + jnp.sum(v).astype(jnp.float32)) * 0.0
    o0 = qf * 0.0 + zkv
    zt = jnp.transpose(jnp.sum(o0, axis=-1), (0, 2, 1))  # [B, H, Tl] zeros
    m0 = zt + NEG_INF
    l0 = zt
    o, m, l, _, _ = lax.fori_loop(0, P, step, (o0, m0, l0, k, v))
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis: str = "sp", causal: bool = False,
                      attn_fn=None):
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all reshards
    sequence↔heads so each member runs *full-sequence* attention on a
    head subset, then reshards back (built on the reference's alltoall,
    fw :2123-2218).  Requires H % P == 0.

    q/k/v: [B, T_local, H, D] → out: [B, T_local, H, D]
    """
    P = lax.axis_size(axis)
    B, Tl, H, D = q.shape
    if H % P != 0:
        raise ValueError(f"heads {H} not divisible by sp={P}")

    def seq_to_heads(x):
        # [B, Tl, H, D] -> [B, P*Tl, H/P, D]
        x = x.reshape(B, Tl, P, H // P, D)
        x = lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)
        return x.reshape(B, P * Tl, H // P, D)  # squeeze the split axis

    def heads_to_seq(x):
        x = x.reshape(B, P * Tl, 1, H // P, D)
        x = lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)
        return x.reshape(B, Tl, H, D)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if attn_fn is None:
        attn_fn = functools.partial(_dense_attention, causal=causal)
    og = attn_fn(qg, kg, vg)
    return heads_to_seq(og)


def _dense_attention(q, k, v, causal: bool = False):
    """Reference dense attention [B, T, H, D] (fp32 accumulation)."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        T = q.shape[1]
        qpos = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        kpos = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        s = jnp.where((qpos >= kpos)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
