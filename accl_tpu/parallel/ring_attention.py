"""Ring attention: sequence/context parallelism over the ICI ring.

SURVEY §5 notes the reference's ring schedules with fused
recv-reduce-send are "precisely ring attention's comm pattern"; this
module builds that pattern as a first-class feature.  Each member holds
a sequence shard of Q/K/V; K/V blocks rotate around the ring (ppermute —
the eager ring relay, fw :1404-1502) while a streaming-softmax
accumulator folds each arriving block into the local output — the
fused_recv_reduce of the firmware (fw :718) with the log-sum-exp
update playing the reduction operator.

Causal masking is blockwise: a K/V block strictly in the future
contributes nothing, the diagonal block takes a triangular mask, past
blocks attend fully.

Call inside shard_map with q/k/v sharded on the sequence axis:
    out = ring_attention(q, k, v, axis="sp", causal=True)
    q,k,v: [B, T_local, H, D] → out: [B, T_local, H, D]
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size as _axis_size

NEG_INF = -1e30


def _flash_defaults(q):
    """Backend-resolved defaults for the SP paths' flash usage: whether
    this process should run the Pallas kernels at all (TPU only — the
    HLO interpreter can't run inside shard_map with check_vma=True), and
    the MXU input format (16-bit activations keep their format, f32
    stays exact)."""
    import jax as _jax

    on_tpu = _jax.default_backend() == "tpu"
    mxu_dt = (q.dtype if q.dtype in (jnp.bfloat16, jnp.float16)
              else jnp.float32)
    return on_tpu, mxu_dt


def _block_attn(q, k, v, bias):
    """Scores + masked streaming-softmax contributions for one K/V block.

    q: [B, Tq, H, D], k/v: [B, Tk, H, D], bias: [Tq, Tk] additive mask.
    Returns (m_blk [B,H,Tq], p [B,H,Tq,Tk], pv [B,Tq,H,D])."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    # [B, H, Tq, Tk]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = s + bias[None, None, :, :]
    m_blk = jnp.max(s, axis=-1)
    p = jnp.exp(s - m_blk[..., None])
    # zero fully-masked rows (m_blk == NEG_INF -> exp(0)=1 garbage)
    dead = m_blk <= NEG_INF / 2
    p = jnp.where(dead[..., None], 0.0, p)
    m_blk = jnp.where(dead, NEG_INF, m_blk)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    return m_blk, p, pv


def zigzag_indices(T: int, P: int):
    """Global sequence permutation for the zigzag causal layout: rank i
    holds chunk i and its mirror chunk 2P-1-i (each T/(2P) long), so
    every rank's total causal work — and, with the zigzag ring schedule,
    its work on EVERY hop — is identical.  The rank-major contiguous
    layout gives rank 0 one live shard and rank P-1 all P, so the ring's
    lockstep hops wait on the heaviest rank; zigzag removes that 2x
    wall-clock loss.

    Returns an int32 index array `perm` such that `x[:, perm]` reorders
    a [B, T, ...] global sequence into zigzag order (shard the result on
    the sequence axis as usual).  Apply the inverse
    (`zigzag_indices_inverse`) to outputs to return to natural order.
    """
    if T % (2 * P) != 0:
        raise ValueError(f"T={T} not divisible by 2*P={2 * P}")
    C = T // (2 * P)
    import numpy as _np

    chunks = []
    for i in range(P):
        chunks.append(_np.arange(i * C, (i + 1) * C))
        j = 2 * P - 1 - i
        chunks.append(_np.arange(j * C, (j + 1) * C))
    return jnp.asarray(_np.concatenate(chunks), jnp.int32)


def zigzag_indices_inverse(T: int, P: int):
    """Inverse of :func:`zigzag_indices` (natural <- zigzag)."""
    import numpy as _np

    perm = _np.asarray(zigzag_indices(T, P))
    inv = _np.empty_like(perm)
    inv[perm] = _np.arange(T, dtype=perm.dtype)
    return jnp.asarray(inv, jnp.int32)


def ring_attention(q, k, v, axis: str = "sp", causal: bool = False,
                   impl: str | None = None, schedule: str = "contiguous",
                   flash_opts: dict | None = None,
                   window: int | None = None):
    """Exact attention over the full (ring-distributed) sequence.

    Per-member shapes [B, T_local, H, D]; the global sequence is the
    rank-major concatenation of shards.  Numerics accumulate in fp32
    regardless of input dtype.

    `impl="flash"` computes each hop's local block with the Pallas flash
    kernel (no [Tl, Tl] score matrix in HBM; MXU-format matmuls follow
    the input dtype) and folds shards by log-sum-exp weighting;
    `impl="dense"` is the jnp reference path.  Default: flash on TPU,
    dense on the CPU rung (the Pallas HLO interpreter can't run inside
    shard_map with check_vma=True — jax#vma dynamic_slice limitation;
    flash-ring CPU tests pass check_vma=False explicitly).

    `schedule="zigzag"` (causal only) expects the global sequence
    permuted by :func:`zigzag_indices` before sharding, and balances the
    causal work exactly across ranks on every hop (each rank computes
    precisely two live half-chunk pairs per hop); the output is in the
    same zigzag order.  `schedule="contiguous"` is the natural layout.

    `flash_opts` forwards static schedule options to the per-hop flash
    kernel (e.g. ``{"q_tiles": 2, "fuse_denom": True}``) so distributed
    callers can run the chip-tuned schedule; ignored by the dense impl.

    `window` (causal + contiguous only, window <= T_local) runs
    SLIDING-WINDOW attention under sequence parallelism: each query's
    visible band fits in its own shard plus the previous one, so the
    schedule is the local windowed block + ONE neighbor hop instead of
    a P-hop ring (see :func:`_ring_attention_windowed`).
    """
    if schedule not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring_attention schedule {schedule!r}")
    if impl is None:
        impl = "flash" if _flash_defaults(q)[0] else "dense"
    if k.shape[2] != q.shape[2]:
        # grouped-query K/V ([B, Tl, G, D], G dividing H): the flash
        # hops consume the grouped layout in place — the ring then
        # rotates H/G-times-smaller shards, a direct ICI-bandwidth win.
        # The dense reference path expands per q head here instead.
        if q.shape[2] % k.shape[2] != 0:
            raise ValueError(
                f"K/V heads {k.shape[2]} must divide q heads "
                f"{q.shape[2]} for GQA")
        if impl == "dense":
            k, v = expand_gqa_kv(k, v, q.shape[2])
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (a sliding "
                             "window is a trailing-context mask)")
        if schedule != "contiguous":
            raise ValueError("window composes with the contiguous "
                             "schedule only (the zigzag layout's split "
                             "chunks break the one-neighbor-hop bound)")
        Tl = q.shape[1]
        if window < 1 or window > Tl:
            raise ValueError(
                f"window={window} must be in [1, T_local={Tl}]: larger "
                "windows span more than one neighbor shard (shard the "
                "sequence into fewer, longer pieces)")
        return _ring_attention_windowed(q, k, v, axis, window, impl,
                                        flash_opts=flash_opts)
    if schedule == "zigzag":
        if not causal:
            raise ValueError("zigzag schedule only makes sense for causal "
                             "attention (non-causal hops are already "
                             "balanced)")
        if impl == "flash":
            return _ring_attention_flash_zigzag(q, k, v, axis,
                                                flash_opts=flash_opts)
        if impl != "dense":
            raise ValueError(f"unknown ring_attention impl {impl!r}")
        return _ring_attention_dense_zigzag(q, k, v, axis)
    if impl == "flash":
        return _ring_attention_flash(q, k, v, axis, causal,
                                     flash_opts=flash_opts)
    if impl != "dense":
        raise ValueError(f"unknown ring_attention impl {impl!r}")
    if causal:
        Tl = q.shape[1]

        def bias_fn(idx, src):
            qpos = idx * Tl + lax.broadcasted_iota(jnp.int32, (Tl, Tl), 0)
            kpos = src * Tl + lax.broadcasted_iota(jnp.int32, (Tl, Tl), 1)
            return jnp.where(qpos >= kpos, 0.0, NEG_INF).astype(jnp.float32)
    else:
        bias_fn = None
    return _dense_ring_loop(q, k, v, axis, bias_fn)


def _dense_ring_loop(q, k, v, axis: str, bias_fn):
    """The dense (jnp) ring schedule shared by the contiguous and zigzag
    layouts: rotate K/V around the ring, fold each arriving shard with a
    streaming-softmax accumulator.  `bias_fn(idx, src) -> [Tl, Tl]`
    computes the additive causal mask for the shard that originated at
    rank `src` (None = unmasked)."""
    P = _axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    perm = [(i, (i + 1) % P) for i in range(P)]

    qf = q.astype(jnp.float32)

    def step(s, carry):
        o, m, lsum, kc, vc = carry
        # current block originated at rank (idx - s) mod P
        src = (idx - s) % P
        bias = (bias_fn(idx, src) if bias_fn is not None
                else jnp.zeros((Tl, Tl), jnp.float32))
        m_blk, p, pv = _block_attn(qf, kc.astype(jnp.float32),
                                   vc.astype(jnp.float32), bias)
        m_new = jnp.maximum(m, m_blk)
        # guard the all-dead case (exp(NEG_INF - NEG_INF) = 1)
        alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        beta = jnp.where(m_blk <= NEG_INF / 2, 0.0, jnp.exp(m_blk - m_new))
        l_new = lsum * alpha + jnp.sum(p, axis=-1) * beta
        o_new = (o * alpha.transpose(0, 2, 1)[..., None]
                 + pv * beta.transpose(0, 2, 1)[..., None])
        # rotate K/V one hop (the ring relay)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return o_new, m_new, l_new, kc, vc

    # accumulators must carry the same device-variance (vma) as the
    # values the loop produces — derive their zeros from q/k/v so the
    # fori_loop carry types match under any mesh composition
    zkv = (jnp.sum(k).astype(jnp.float32)
           + jnp.sum(v).astype(jnp.float32)) * 0.0
    o0 = qf * 0.0 + zkv
    zt = jnp.transpose(jnp.sum(o0, axis=-1), (0, 2, 1))  # [B, H, Tl] zeros
    m0 = zt + NEG_INF
    l0 = zt
    o, m, lsum, _, _ = lax.fori_loop(0, P, step, (o0, m0, l0, k, v))
    lsum = jnp.maximum(lsum, 1e-30)
    out = o / lsum.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _lse_merge(o, lse, o_i, lse_i, _NI=NEG_INF):
    """lse-weighted merge of normalized partial attentions (exact; dead
    partials carry lse = -inf and weight 0).  o/o_i: [B, T, H, D] (o is
    the fp32 running carry), lse/lse_i: [B, H, T].  Returns (o', lse')."""
    m_new = jnp.maximum(lse, lse_i)
    safe = jnp.where(m_new <= _NI / 2, 0.0, m_new)
    w_r = jnp.where(lse <= _NI / 2, 0.0, jnp.exp(lse - safe))
    w_i = jnp.where(lse_i <= _NI / 2, 0.0, jnp.exp(lse_i - safe))
    # normal-range epsilon: 1e-38 is subnormal f32 and flushes to zero
    # under FTZ, making the both-dead case 0/0 = NaN
    tot = jnp.maximum(w_r + w_i, 1e-30)
    wr4 = (w_r / tot).transpose(0, 2, 1)[..., None]  # [B, T, H, 1]
    wi4 = (w_i / tot).transpose(0, 2, 1)[..., None]
    o_new = o * wr4 + o_i.astype(jnp.float32) * wi4
    lse_new = jnp.where((w_r + w_i) == 0.0, jnp.full_like(m_new, _NI),
                        safe + jnp.log(tot))
    return o_new, lse_new


def _ring_attention_dense_zigzag(q, k, v, axis: str):
    """Dense (jnp) zigzag schedule: the shared ring loop with the causal
    bias computed from the zigzag GLOBAL positions of the local rows
    (chunk idx and its mirror 2P-1-idx) instead of a contiguous
    offset."""
    P = _axis_size(axis)
    Tl = q.shape[1]
    if Tl % 2 != 0:
        raise ValueError(f"zigzag needs an even local length, got {Tl}")
    C = Tl // 2

    def positions(r):
        ar = lax.iota(jnp.int32, C)
        return jnp.concatenate([r * C + ar, (2 * P - 1 - r) * C + ar])

    def bias_fn(idx, src):
        qpos, kpos = positions(idx), positions(src)
        return jnp.where(qpos[:, None] >= kpos[None, :], 0.0,
                         NEG_INF).astype(jnp.float32)

    return _dense_ring_loop(q, k, v, axis, bias_fn)


def _ring_attention_flash_zigzag(q, k, v, axis: str,
                                 flash_opts: dict | None = None):
    """Flash-backed zigzag causal ring schedule — exact per-hop load
    balance.

    Each rank's local row holds chunks (idx, 2P-1-idx), each C = Tl/2
    long.  With arriving chunks (a, 2P-1-a), a = (idx - s) mod P, the
    chunk-pair liveness works out to EXACTLY two live half-chunk flash
    calls per rank per hop (three half-size ones on the diagonal hop,
    simultaneously for all ranks):

      (qh, kl): always live, full          [kl = chunk a, qh = 2P-1-idx]
      a < idx:  (ql, kl) full              [ql's past]
      a == idx: (ql, kl) + (qh, kh) causal [the diagonal hop, s = 0]
      a > idx:  (qh, kh) full              [kh = 2P-1-a <= 2P-1-idx]
      (ql, kh): never live                 [kh >= P > ql's chunk]

    so the lockstep ppermute never waits on a heavier neighbor — the
    contiguous causal schedule degrades to the heaviest rank (P live
    shards) while the average is P/2."""
    from ..ops.flash import NEG_INF as _NI
    from ..ops.flash import flash_attention_lse

    P = _axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    if Tl % 2 != 0:
        raise ValueError(f"zigzag needs an even local length, got {Tl}")
    C = Tl // 2
    perm = [(i, (i + 1) % P) for i in range(P)]
    on_tpu, mxu_dt = _flash_defaults(q)
    interpret = not on_tpu

    ql, qh = q[:, :C], q[:, C:]

    def flash(qx, kx, vx, causal):
        return flash_attention_lse(qx, kx, vx, causal=causal,
                                   interpret=interpret, mxu_dtype=mxu_dt,
                                   **(flash_opts or {}))

    def dead(kx, vx):
        # zeros carrying the same device-variance as the live branches
        zkv = (jnp.sum(kx).astype(jnp.float32)
               + jnp.sum(vx).astype(jnp.float32)) * 0.0
        o_z = (ql.astype(jnp.float32) * 0.0 + zkv).astype(q.dtype)
        lse_z = jnp.transpose(
            jnp.sum(o_z.astype(jnp.float32), axis=-1), (0, 2, 1)) + _NI
        return o_z, lse_z

    def step(s, carry):
        o_lo, lse_lo, o_hi, lse_hi, kc, vc = carry
        src = (idx - s) % P
        kl, kh = kc[:, :C], kc[:, C:]
        vl, vh = vc[:, :C], vc[:, C:]

        # always-live pair: qh attends the arriving low chunk fully
        o_hb, lse_hb = flash(qh, kl, vl, causal=False)

        # branch on the arriving low chunk's position vs ours
        def past(_):   # a < idx: ql's past arrived
            o1, s1 = flash(ql, kl, vl, causal=False)
            o2, s2 = dead(kh, vh)
            return o1, s1, o2, s2

        def diag(_):   # a == idx: both diagonals (hop 0)
            o1, s1 = flash(ql, kl, vl, causal=True)
            o2, s2 = flash(qh, kh, vh, causal=True)
            return o1, s1, o2, s2

        def future(_):  # a > idx: qh's mirror-past arrived
            o1, s1 = dead(kl, vl)
            o2, s2 = flash(qh, kh, vh, causal=False)
            return o1, s1, o2, s2

        branch = jnp.where(src == idx, 1, jnp.where(src < idx, 0, 2))
        o_li, lse_li, o_he, lse_he = lax.switch(
            branch, (past, diag, future), None)

        o_lo, lse_lo = _lse_merge(o_lo, lse_lo, o_li, lse_li, _NI)
        o_hi, lse_hi = _lse_merge(o_hi, lse_hi, o_hb, lse_hb, _NI)
        o_hi, lse_hi = _lse_merge(o_hi, lse_hi, o_he, lse_he, _NI)

        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return o_lo, lse_lo, o_hi, lse_hi, kc, vc

    zkv = (jnp.sum(k).astype(jnp.float32)
           + jnp.sum(v).astype(jnp.float32)) * 0.0
    o0 = ql.astype(jnp.float32) * 0.0 + zkv
    lse0 = jnp.transpose(jnp.sum(o0, axis=-1), (0, 2, 1)) + NEG_INF
    o_lo, _sl, o_hi, _sh, _, _ = lax.fori_loop(
        0, P, step, (o0, lse0, o0, lse0, k, v))
    return jnp.concatenate([o_lo, o_hi], axis=1).astype(q.dtype)




def _banded_cross_lse(q, kk, vv, offset: int, window: int, live):
    """lse-emitting dense attention of a q shard against ONE K/V shard
    under a trailing window, in relative coordinates: q row i sits
    `offset + i - j` positions after k row j; a cell contributes iff
    0 <= offset + i - j < window (the >= 0 half IS causality).  `live`
    is a traced bool gating the whole block (rank 0 has no previous
    shard).  Returns (o [B, T, H, D] normalized, lse [B, H, T] natural
    log) with dead rows at lse = -inf / o = 0 — the _lse_merge
    contract, so partial blocks fold exactly."""
    B, T, H, D = q.shape
    Tk = kk.shape[1]
    if kk.shape[2] != H:
        kk, vv = expand_gqa_kv(kk, vv, H)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   kk.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    d = (offset + lax.broadcasted_iota(jnp.int32, (T, Tk), 0)
         - lax.broadcasted_iota(jnp.int32, (T, Tk), 1))
    keep = (d >= 0) & (d < window) & live
    s = jnp.where(keep[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    shift = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(s - shift)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    lsum = jnp.sum(p, axis=-1, keepdims=True)
    # epsilon must be a NORMAL f32: 1e-38 is subnormal and flushes to
    # zero under FTZ, turning the dead-row guard into 0/0 = NaN
    out = jnp.einsum("bhqk,bkhd->bqhd", p / jnp.maximum(lsum, 1e-30),
                     vv.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    lse = jnp.where(lsum[..., 0] == 0.0, NEG_INF,
                    shift[..., 0] + jnp.log(jnp.maximum(lsum[..., 0],
                                                        1e-30)))
    return out, lse  # o fp32, lse [B, H, T]


def _ring_attention_windowed(q, k, v, axis: str, window: int,
                             impl: str, flash_opts: dict | None = None):
    """Sliding-window attention under sequence parallelism (contiguous
    shards, causal, window <= T_local): every query's visible band
    lies within its OWN shard plus the previous one, so the full ring
    collapses to the local block + ONE neighbor hop — O(1) in the ring
    size where the unwindowed ring is O(P) (the Mistral-style
    long-context composition the r4 build rejected outright).

    Local block: the shard's own causal window attention (the flash
    grid schedule's bounded-liveness path on TPU).  Boundary block:
    a banded dense cross against the previous shard's K/V (one block
    per rank — it cannot dominate at scale).  Exact merge by lse."""
    P = _axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape

    if impl == "flash":
        from ..ops.flash import flash_attention_lse

        on_tpu, mxu_dt = _flash_defaults(q)
        opts = dict(flash_opts or {})
        opts.setdefault("interpret", not on_tpu)
        opts.setdefault("mxu_dtype", mxu_dt)
        o_loc, lse_loc = flash_attention_lse(q, k, v, causal=True,
                                             window=window, **opts)
        o_loc = o_loc.astype(jnp.float32)
    elif impl == "dense":
        # local block through the SAME banded helper (offset 0: the
        # d >= 0 arm is exactly the causal mask)
        o_loc, lse_loc = _banded_cross_lse(q, k, v, 0, window,
                                           jnp.bool_(True))
    else:
        raise ValueError(f"unknown ring_attention impl {impl!r}")

    Wn = window - 1  # boundary band width (window=1: self-only)
    if Wn == 0:
        return o_loc.astype(q.dtype)

    # ONE hop, STATICALLY SLICED to the live band: only the previous
    # shard's last Wn rows are visible to anyone here, and only this
    # shard's first Wn queries can see them — the hop moves
    # O(window) K/V bytes and the cross scores O(window^2) cells, not
    # O(Tl^2) (at Tl >> window the full-shard version would dominate
    # exactly where the windowed path is meant to win)
    perm = [(i, (i + 1) % P) for i in range(P)]
    ktail = lax.ppermute(k[:, Tl - Wn:], axis, perm)
    vtail = lax.ppermute(v[:, Tl - Wn:], axis, perm)
    # tail row j' is global position (prev shard) Tl - Wn + j', so a
    # local query i sits i + Wn - j' positions after it
    o_bs, lse_bs = _banded_cross_lse(q[:, :Wn], ktail, vtail, Wn,
                                     window, idx > 0)
    H_q = q.shape[2]
    o_b = jnp.zeros((B, Tl, H_q, D), jnp.float32).at[:, :Wn].set(o_bs)
    lse_b = jnp.full((B, H_q, Tl), NEG_INF,
                     jnp.float32).at[:, :, :Wn].set(lse_bs)
    o, _ = _lse_merge(o_loc, lse_loc, o_b, lse_b)
    return o.astype(q.dtype)


def _ring_attention_flash(q, k, v, axis: str, causal: bool,
                          flash_opts: dict | None = None):
    """Flash-backed ring schedule: each hop runs the K/V-resident flash
    kernel on the local (Q shard, arriving K/V shard) pair and the
    results merge by lse weighting — the streaming-softmax fold lifted
    one level, from k-blocks within a shard to shards around the ring.

    Causality is blockwise by construction: an arriving shard is either
    fully in the past (unmasked flash), the diagonal shard (causal
    flash), or fully in the future (contributes nothing) — so the kernel
    itself only ever needs its LOCAL causal mask.
    """
    import jax as _jax

    from ..ops.flash import NEG_INF as _NI
    from ..ops.flash import flash_attention_lse

    P = _axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    perm = [(i, (i + 1) % P) for i in range(P)]
    on_tpu, mxu_dt = _flash_defaults(q)
    interpret = not on_tpu

    def hop_full(kv):
        kc, vc = kv
        return flash_attention_lse(q, kc, vc, causal=False,
                                   interpret=interpret, mxu_dtype=mxu_dt,
                                   **(flash_opts or {}))

    def hop_diag(kv):
        kc, vc = kv
        return flash_attention_lse(q, kc, vc, causal=True,
                                   interpret=interpret, mxu_dtype=mxu_dt,
                                   **(flash_opts or {}))

    def hop_dead(kv):
        # zeros derived from q AND the rotating k/v so this branch's
        # outputs carry the same device-variance (vma) as the flash
        # branches — lax.switch requires matching output types
        kc, vc = kv
        zkv = (jnp.sum(kc).astype(jnp.float32)
               + jnp.sum(vc).astype(jnp.float32)) * 0.0
        o_z = (q.astype(jnp.float32) * 0.0 + zkv).astype(q.dtype)
        lse_z = jnp.transpose(
            jnp.sum(o_z.astype(jnp.float32), axis=-1), (0, 2, 1)) + _NI
        return o_z, lse_z

    def step(s, carry):
        o, lse, kc, vc = carry
        src = (idx - s) % P
        if causal:
            branch = jnp.where(src == idx, 1,
                               jnp.where(src < idx, 0, 2))
            o_i, lse_i = lax.switch(branch, (hop_full, hop_diag, hop_dead),
                                    (kc, vc))
        else:
            o_i, lse_i = hop_full((kc, vc))
        # the running output carry stays fp32 for the whole ring (one
        # downcast after the loop), matching the dense path's contract
        o_new, lse_new = _lse_merge(o, lse, o_i, lse_i, _NI)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return o_new, lse_new, kc, vc

    # carry zeros derive from q/k/v so the device-variance types match
    # under any mesh composition (see the dense path's note)
    zkv = (jnp.sum(k).astype(jnp.float32)
           + jnp.sum(v).astype(jnp.float32)) * 0.0
    o0 = q.astype(jnp.float32) * 0.0 + zkv
    lse0 = jnp.transpose(jnp.sum(o0, axis=-1), (0, 2, 1)) + NEG_INF
    o, _lse, _, _ = lax.fori_loop(0, P, step, (o0, lse0, k, v))
    return o.astype(q.dtype)


def ulysses_attention(q, k, v, axis: str = "sp", causal: bool = False,
                      attn_fn=None, attn_fn_gqa_aware: bool = False):
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all reshards
    sequence↔heads so each member runs *full-sequence* attention on a
    head subset, then reshards back (built on the reference's alltoall,
    fw :2123-2218).  Requires H % P == 0.

    q: [B, T_local, H, D], k/v: [B, T_local, H or G, D] (grouped-query
    K/V reshard their own smaller head axis — G must also divide by P)
    → out: [B, T_local, H, D]

    A caller-supplied ``attn_fn`` receives EXPANDED K/V under GQA by
    default (safe for non-GQA-aware callables; correctness beats the
    bandwidth saving).  Pass ``attn_fn_gqa_aware=True`` when the
    callable handles a smaller K/V head axis itself (e.g. a partial of
    ops.flash.flash_attention) to keep the grouped layout and its
    HBM/memory saving.
    """
    P = _axis_size(axis)
    B, Tl, H, D = q.shape
    G = k.shape[2]
    if H % P != 0:
        raise ValueError(f"heads {H} not divisible by sp={P}")
    if G != H and (G % P != 0 or H % G != 0):
        raise ValueError(f"K/V heads {G} must divide q heads {H} and "
                         f"be divisible by sp={P} for Ulysses GQA")

    def seq_to_heads(x):
        # [B, Tl, h, D] -> [B, P*Tl, h/P, D] (h = that tensor's heads)
        h = x.shape[2]
        x = x.reshape(B, Tl, P, h // P, D)
        x = lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)
        return x.reshape(B, P * Tl, h // P, D)  # squeeze the split axis

    def heads_to_seq(x):
        x = x.reshape(B, P * Tl, 1, H // P, D)
        x = lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)
        return x.reshape(B, Tl, H, D)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # caller-supplied fns get expansion unless declared GQA-aware
    attn_fn_wants_expansion = (attn_fn is not None
                               and not attn_fn_gqa_aware)
    if attn_fn is None:
        import jax as _jax
        if _jax.default_backend() == "tpu":
            # full-sequence local attention on the head subset runs the
            # flash kernel (same backend-resolved default as ring)
            from ..ops.flash import flash_attention

            mxu_dt = (q.dtype if q.dtype in (jnp.bfloat16, jnp.float16)
                      else jnp.float32)
            attn_fn = functools.partial(flash_attention, causal=causal,
                                        mxu_dtype=mxu_dt)
        else:
            attn_fn = functools.partial(_dense_attention, causal=causal)
            attn_fn_wants_expansion = True
    if kg.shape[2] != qg.shape[2] and attn_fn_wants_expansion:
        # a grouped head subset reaches a non-flash attention callable
        # (the dense default, or any caller-supplied fn — assumed NOT
        # GQA-aware; correctness beats the expansion saving there)
        kg, vg = expand_gqa_kv(kg, vg, qg.shape[2])
    og = attn_fn(qg, kg, vg)
    return heads_to_seq(og)


def expand_gqa_kv(k, v, n_q_heads: int):
    """Expand grouped K/V ([B, T, G, D]) to one head per q head by
    repeating each K/V head across its CONSECUTIVE group — the same
    row-sharing layout as the flash kernel's GQA index maps (q head n
    reads K/V head n // (H/G)).  The one place the expansion layout is
    defined; dense reference paths call this instead of repeating
    inline."""
    group = n_q_heads // k.shape[2]
    if group == 1:
        return k, v
    return (jnp.repeat(k, group, axis=2), jnp.repeat(v, group, axis=2))


def _dense_attention(q, k, v, causal: bool = False,
                     window: int | None = None):
    """Reference dense attention [B, T, H, D] (fp32 accumulation).
    `window` (causal only) restricts each row to its trailing `window`
    columns — the banded reference the flash grid schedules match."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        T = q.shape[1]
        qpos = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        kpos = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        keep = qpos >= kpos
        if window is not None:
            keep = keep & (qpos - kpos < window)
        s = jnp.where(keep[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
