"""Ring attention: sequence/context parallelism over the ICI ring.

SURVEY §5 notes the reference's ring schedules with fused
recv-reduce-send are "precisely ring attention's comm pattern"; this
module builds that pattern as a first-class feature.  Each member holds
a sequence shard of Q/K/V; K/V blocks rotate around the ring (ppermute —
the eager ring relay, fw :1404-1502) while a streaming-softmax
accumulator folds each arriving block into the local output — the
fused_recv_reduce of the firmware (fw :718) with the log-sum-exp
update playing the reduction operator.

Causal masking is blockwise: a K/V block strictly in the future
contributes nothing, the diagonal block takes a triangular mask, past
blocks attend fully.

Call inside shard_map with q/k/v sharded on the sequence axis:
    out = ring_attention(q, k, v, axis="sp", causal=True)
    q,k,v: [B, T_local, H, D] → out: [B, T_local, H, D]
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _flash_defaults(q):
    """Backend-resolved defaults for the SP paths' flash usage: whether
    this process should run the Pallas kernels at all (TPU only — the
    HLO interpreter can't run inside shard_map with check_vma=True), and
    the MXU input format (16-bit activations keep their format, f32
    stays exact)."""
    import jax as _jax

    on_tpu = _jax.default_backend() == "tpu"
    mxu_dt = (q.dtype if q.dtype in (jnp.bfloat16, jnp.float16)
              else jnp.float32)
    return on_tpu, mxu_dt


def _block_attn(q, k, v, bias):
    """Scores + masked streaming-softmax contributions for one K/V block.

    q: [B, Tq, H, D], k/v: [B, Tk, H, D], bias: [Tq, Tk] additive mask.
    Returns (m_blk [B,H,Tq], p [B,H,Tq,Tk], pv [B,Tq,H,D])."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    # [B, H, Tq, Tk]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = s + bias[None, None, :, :]
    m_blk = jnp.max(s, axis=-1)
    p = jnp.exp(s - m_blk[..., None])
    # zero fully-masked rows (m_blk == NEG_INF -> exp(0)=1 garbage)
    dead = m_blk <= NEG_INF / 2
    p = jnp.where(dead[..., None], 0.0, p)
    m_blk = jnp.where(dead, NEG_INF, m_blk)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    return m_blk, p, pv


def ring_attention(q, k, v, axis: str = "sp", causal: bool = False,
                   impl: str | None = None):
    """Exact attention over the full (ring-distributed) sequence.

    Per-member shapes [B, T_local, H, D]; the global sequence is the
    rank-major concatenation of shards.  Numerics accumulate in fp32
    regardless of input dtype.

    `impl="flash"` computes each hop's local block with the Pallas flash
    kernel (no [Tl, Tl] score matrix in HBM; MXU-format matmuls follow
    the input dtype) and folds shards by log-sum-exp weighting;
    `impl="dense"` is the jnp reference path.  Default: flash on TPU,
    dense on the CPU rung (the Pallas HLO interpreter can't run inside
    shard_map with check_vma=True — jax#vma dynamic_slice limitation;
    flash-ring CPU tests pass check_vma=False explicitly).
    """
    if impl is None:
        impl = "flash" if _flash_defaults(q)[0] else "dense"
    if impl == "flash":
        return _ring_attention_flash(q, k, v, axis, causal)
    if impl != "dense":
        raise ValueError(f"unknown ring_attention impl {impl!r}")
    P = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    perm = [(i, (i + 1) % P) for i in range(P)]

    qf = q.astype(jnp.float32)

    def step(s, carry):
        o, m, l, kc, vc = carry
        # current block originated at rank (idx - s) mod P
        src = (idx - s) % P
        if causal:
            qpos = idx * Tl + lax.broadcasted_iota(jnp.int32, (Tl, Tl), 0)
            kpos = src * Tl + lax.broadcasted_iota(jnp.int32, (Tl, Tl), 1)
            bias = jnp.where(qpos >= kpos, 0.0, NEG_INF).astype(jnp.float32)
        else:
            bias = jnp.zeros((Tl, Tl), jnp.float32)
        m_blk, p, pv = _block_attn(qf, kc.astype(jnp.float32),
                                   vc.astype(jnp.float32), bias)
        m_new = jnp.maximum(m, m_blk)
        # guard the all-dead case (exp(NEG_INF - NEG_INF) = 1)
        alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        beta = jnp.where(m_blk <= NEG_INF / 2, 0.0, jnp.exp(m_blk - m_new))
        l_new = l * alpha + jnp.sum(p, axis=-1) * beta
        o_new = (o * alpha.transpose(0, 2, 1)[..., None]
                 + pv * beta.transpose(0, 2, 1)[..., None])
        # rotate K/V one hop (the ring relay)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return o_new, m_new, l_new, kc, vc

    # accumulators must carry the same device-variance (vma) as the
    # values the loop produces — derive their zeros from q/k/v so the
    # fori_loop carry types match under any mesh composition
    zkv = (jnp.sum(k).astype(jnp.float32)
           + jnp.sum(v).astype(jnp.float32)) * 0.0
    o0 = qf * 0.0 + zkv
    zt = jnp.transpose(jnp.sum(o0, axis=-1), (0, 2, 1))  # [B, H, Tl] zeros
    m0 = zt + NEG_INF
    l0 = zt
    o, m, l, _, _ = lax.fori_loop(0, P, step, (o0, m0, l0, k, v))
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_attention_flash(q, k, v, axis: str, causal: bool):
    """Flash-backed ring schedule: each hop runs the K/V-resident flash
    kernel on the local (Q shard, arriving K/V shard) pair and the
    results merge by lse weighting — the streaming-softmax fold lifted
    one level, from k-blocks within a shard to shards around the ring.

    Causality is blockwise by construction: an arriving shard is either
    fully in the past (unmasked flash), the diagonal shard (causal
    flash), or fully in the future (contributes nothing) — so the kernel
    itself only ever needs its LOCAL causal mask.
    """
    import jax as _jax

    from ..ops.flash import NEG_INF as _NI
    from ..ops.flash import flash_attention_lse

    P = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    B, Tl, H, D = q.shape
    perm = [(i, (i + 1) % P) for i in range(P)]
    on_tpu, mxu_dt = _flash_defaults(q)
    interpret = not on_tpu

    def hop_full(kv):
        kc, vc = kv
        return flash_attention_lse(q, kc, vc, causal=False,
                                   interpret=interpret, mxu_dtype=mxu_dt)

    def hop_diag(kv):
        kc, vc = kv
        return flash_attention_lse(q, kc, vc, causal=True,
                                   interpret=interpret, mxu_dtype=mxu_dt)

    def hop_dead(kv):
        # zeros derived from q AND the rotating k/v so this branch's
        # outputs carry the same device-variance (vma) as the flash
        # branches — lax.switch requires matching output types
        kc, vc = kv
        zkv = (jnp.sum(kc).astype(jnp.float32)
               + jnp.sum(vc).astype(jnp.float32)) * 0.0
        o_z = (q.astype(jnp.float32) * 0.0 + zkv).astype(q.dtype)
        lse_z = jnp.transpose(
            jnp.sum(o_z.astype(jnp.float32), axis=-1), (0, 2, 1)) + _NI
        return o_z, lse_z

    def step(s, carry):
        o, lse, kc, vc = carry
        src = (idx - s) % P
        if causal:
            branch = jnp.where(src == idx, 1,
                               jnp.where(src < idx, 0, 2))
            o_i, lse_i = lax.switch(branch, (hop_full, hop_diag, hop_dead),
                                    (kc, vc))
        else:
            o_i, lse_i = hop_full((kc, vc))
        # lse-weighted merge of normalized partials (exact; dead shards
        # carry lse = -inf and weight 0)
        m_new = jnp.maximum(lse, lse_i)
        safe = jnp.where(m_new <= _NI / 2, 0.0, m_new)
        w_r = jnp.where(lse <= _NI / 2, 0.0, jnp.exp(lse - safe))
        w_i = jnp.where(lse_i <= _NI / 2, 0.0, jnp.exp(lse_i - safe))
        tot = jnp.maximum(w_r + w_i, 1e-38)
        wr4 = (w_r / tot).transpose(0, 2, 1)[..., None]  # [B, Tl, H, 1]
        wi4 = (w_i / tot).transpose(0, 2, 1)[..., None]
        # the running output carry stays fp32 for the whole ring (one
        # downcast after the loop), matching the dense path's contract
        o_new = o * wr4 + o_i.astype(jnp.float32) * wi4
        lse_new = jnp.where((w_r + w_i) == 0.0, jnp.full_like(m_new, _NI),
                            safe + jnp.log(tot))
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return o_new, lse_new, kc, vc

    # carry zeros derive from q/k/v so the device-variance types match
    # under any mesh composition (see the dense path's note)
    zkv = (jnp.sum(k).astype(jnp.float32)
           + jnp.sum(v).astype(jnp.float32)) * 0.0
    o0 = q.astype(jnp.float32) * 0.0 + zkv
    lse0 = jnp.transpose(jnp.sum(o0, axis=-1), (0, 2, 1)) + NEG_INF
    o, _lse, _, _ = lax.fori_loop(0, P, step, (o0, lse0, k, v))
    return o.astype(q.dtype)


def ulysses_attention(q, k, v, axis: str = "sp", causal: bool = False,
                      attn_fn=None):
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all reshards
    sequence↔heads so each member runs *full-sequence* attention on a
    head subset, then reshards back (built on the reference's alltoall,
    fw :2123-2218).  Requires H % P == 0.

    q/k/v: [B, T_local, H, D] → out: [B, T_local, H, D]
    """
    P = lax.axis_size(axis)
    B, Tl, H, D = q.shape
    if H % P != 0:
        raise ValueError(f"heads {H} not divisible by sp={P}")

    def seq_to_heads(x):
        # [B, Tl, H, D] -> [B, P*Tl, H/P, D]
        x = x.reshape(B, Tl, P, H // P, D)
        x = lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)
        return x.reshape(B, P * Tl, H // P, D)  # squeeze the split axis

    def heads_to_seq(x):
        x = x.reshape(B, P * Tl, 1, H // P, D)
        x = lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)
        return x.reshape(B, Tl, H, D)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if attn_fn is None:
        import jax as _jax
        if _jax.default_backend() == "tpu":
            # full-sequence local attention on the head subset runs the
            # flash kernel (same backend-resolved default as ring)
            from ..ops.flash import flash_attention

            mxu_dt = q.dtype if q.dtype in (jnp.bfloat16, jnp.float16)                 else jnp.float32
            attn_fn = functools.partial(flash_attention, causal=causal,
                                        mxu_dtype=mxu_dt)
        else:
            attn_fn = functools.partial(_dense_attention, causal=causal)
    og = attn_fn(qg, kg, vg)
    return heads_to_seq(og)


def _dense_attention(q, k, v, causal: bool = False):
    """Reference dense attention [B, T, H, D] (fp32 accumulation)."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        T = q.shape[1]
        qpos = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        kpos = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        s = jnp.where((qpos >= kpos)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
