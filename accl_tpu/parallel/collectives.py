"""Functional SPMD collectives — the XLA lowering layer.

These are meant to be called *inside* `shard_map`/`pjit`-traced functions
over a mesh axis.  Each maps one reference collective onto its XLA HLO
equivalent, which the TPU compiler schedules over ICI links (BASELINE
north star: HLO collectives replace the CCLO offload engine):

| reference firmware schedule           | here                          |
|---------------------------------------|-------------------------------|
| segmented ring allreduce (fw :1888)   | lax.psum (+ ring_all_reduce)  |
| ring allgather (fw :1299)             | lax.all_gather                |
| ring reduce_scatter (fw :1748)        | lax.psum_scatter              |
| fused flat-tree alltoall (fw :2123)   | lax.all_to_all                |
| tree/flat bcast (fw :798)             | all_gather + index            |
| daisy-chain/tree reduce (fw :1509)    | psum/pmax (root keeps)        |
| tagged send/recv (fw :575/:655)       | lax.ppermute pairs            |

The explicit `ring_*` variants express the reference's ring schedules
directly with `ppermute` steps — useful when manual overlap beats XLA's
built-in lowering, and as the scheduling skeleton the Pallas kernels
(accl_tpu.ops.ring) implement with remote DMA.
"""
from __future__ import annotations


import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size as _axis_size


# ---------------------------------------------------------------------------
# direct XLA lowerings
# ---------------------------------------------------------------------------
def all_reduce(x, axis: str = "rank", op: str = "sum"):
    """All-reduce over a mesh axis (fw allreduce :1855-2075)."""
    if op == "sum":
        return lax.psum(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    raise ValueError(f"unknown reduce op {op!r}")


def reduce(x, root: int, axis: str = "rank", op: str = "sum"):
    """Rooted reduce: every member computes the reduction, the caller
    keeps the root's copy (fw reduce :1509-1744).  On TPU the replicated
    compute is free relative to the collective itself."""
    return all_reduce(x, axis, op)


def all_gather(x, axis: str = "rank", tiled: bool = True, gather_axis: int = 0):
    """All-gather over a mesh axis (fw allgather :1299-1505)."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: str = "rank", scatter_axis: int = 0):
    """Reduce-scatter over a mesh axis (fw reduce_scatter :1748-1852)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                            tiled=True)


def all_to_all(x, axis: str = "rank", split_axis: int = 0,
               concat_axis: int = 0, tiled: bool = True):
    """All-to-all personalized exchange (fw all_to_all :2123-2218)."""
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def broadcast(x, root: int, axis: str = "rank"):
    """Broadcast the root's value to every member (fw bcast :798-990)."""
    return lax.all_gather(x, axis)[root]


def scatter(x, root: int, axis: str = "rank"):
    """Scatter the root's rank-major blocks: member i receives block i
    (fw scatter :994-1125).  `x` must have leading dim = axis size."""
    row = lax.all_gather(x, axis)[root]
    idx = lax.axis_index(axis)
    return lax.dynamic_index_in_dim(row, idx, axis=0, keepdims=False)


def gather(x, root: int, axis: str = "rank"):
    """Gather members' blocks; caller keeps the root's copy
    (fw gather :1130-1296)."""
    return lax.all_gather(x, axis)


def ppermute(x, perm, axis: str = "rank"):
    """Point-to-point permutation — the tagged send/recv equivalent."""
    return lax.ppermute(x, axis, perm)


def send_recv(x, src: int, dst: int, axis: str = "rank"):
    """Single-pair transfer: `dst` receives `src`'s value, everyone else
    receives zeros (fw send/recv :575-712)."""
    return lax.ppermute(x, axis, [(src, dst)])


def barrier(axis: str = "rank"):
    """Synchronization via a trivial psum (fw barrier :2077-2120 —
    gather+scatter of empty messages; on TPU any collective is a sync)."""
    return lax.psum(jnp.ones((), jnp.int32), axis)


# ---------------------------------------------------------------------------
# explicit ring schedules (the reference's firmware schedules, expressed
# with ppermute steps; XLA overlaps consecutive steps across ICI)
# ---------------------------------------------------------------------------
def ring_reduce_scatter(x, axis: str = "rank"):
    """Ring reduce-scatter (fw :1782-1850): P-1 steps, each sending the
    running partial one hop forward and folding the arriving chunk.
    `x`: [P * n, ...] per member → returns member's reduced chunk [n, ...].
    """
    size = _axis_size(axis)
    idx = lax.axis_index(axis)
    n = x.shape[0] // size
    chunks = x.reshape((size, n) + x.shape[1:])
    fwd = [(i, (i + 1) % size) for i in range(size)]

    def step(s, carry):
        # the chunk sent this step is (idx - 1 - s) mod size; only the
        # arriving chunk index below is needed to fold the reduction
        partial = carry
        moved = lax.ppermute(partial, axis, fwd)
        recv_c = (idx - 2 - s) % size
        return moved + jnp.take(chunks, recv_c, axis=0)

    first = jnp.take(chunks, (idx - 1) % size, axis=0)
    # s=0 already "holds" chunk (idx-1); fold P-1 arrivals
    out = lax.fori_loop(0, size - 1, step, first)
    return out


def ring_all_gather(x, axis: str = "rank"):
    """Ring all-gather (fw :1404-1502): P-1 steps, forwarding the newest
    block each step.  `x`: [n, ...] → [P * n, ...] in rank-major order."""
    size = _axis_size(axis)
    idx = lax.axis_index(axis)

    def step(s, carry):
        out, cur = carry
        cur = lax.ppermute(cur, axis, [(i, (i + 1) % size) for i in range(size)])
        origin = (idx - 1 - s) % size
        out = lax.dynamic_update_slice_in_dim(out, cur[None], origin * 1,
                                              axis=0)
        return out, cur

    out0 = jnp.zeros((size,) + x.shape, x.dtype)
    out0 = lax.dynamic_update_slice_in_dim(out0, x[None], idx * 1, axis=0)
    out, _ = lax.fori_loop(0, size - 1, step, (out0, x))
    return out.reshape((size * x.shape[0],) + x.shape[1:])


def ring_all_reduce(x, axis: str = "rank"):
    """Segmented ring allreduce = ring reduce-scatter + ring all-gather
    fused (fw :1888-2071).  `x`: [P * n, ...] with P | x.shape[0]."""
    chunk = ring_reduce_scatter(x, axis)
    return ring_all_gather(chunk, axis)


def hierarchical_all_reduce(x, ici_axis: str, dcn_axis: str):
    """Two-level allreduce for multi-slice meshes: reduce-scatter inside
    the slice (ICI), all-reduce the shards across slices (DCN), then
    all-gather back inside the slice.  Crosses DCN with 1/|ici| of the
    bytes a flat psum over both axes would — the same
    bandwidth-hierarchy trick as the reference's ring schedules over its
    100G POE links (fw allreduce :1888-2071), applied to the ICI/DCN
    hierarchy of a multi-slice mesh (`make_hybrid_mesh`).

    `x`'s leading dim must be divisible by the ici axis size.
    """
    shard = lax.psum_scatter(x, ici_axis, scatter_dimension=0, tiled=True)
    shard = lax.psum(shard, dcn_axis)
    return lax.all_gather(shard, ici_axis, axis=0, tiled=True)
