"""SPMD parallelism layer: the TPU-native functional face of the
framework.

Where the driver API (accl_tpu.ACCL) mirrors the reference's imperative
per-rank interface, this package is the idiomatic JAX surface: explicit
meshes, sharding-annotated functional collectives, and the parallelism
strategies (data/tensor/pipeline/expert/sequence) the reference's
collectives exist to serve (SURVEY §2.8)."""

from .collectives import (  # noqa: F401
    all_gather,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    gather,
    ppermute,
    reduce,
    reduce_scatter,
    ring_all_gather,
    ring_all_reduce,
    ring_reduce_scatter,
    scatter,
    send_recv,
)
from .mesh import MeshConfig, make_mesh  # noqa: F401
from .ring_attention import (  # noqa: F401
    ring_attention,
    ulysses_attention,
)
from .strategies import (  # noqa: F401
    column_parallel,
    expert_combine,
    expert_dispatch,
    pipeline_apply,
    row_parallel,
    sync_gradients,
    zero_shard_gradients,
    zero_unshard_params,
)
