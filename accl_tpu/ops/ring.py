"""Ring collectives as Pallas TPU kernels over inter-chip RDMA.

This is the firmware's ring schedule family (segmented ring allreduce
fw :1888-2071, ring allgather :1299-1505, ring reduce_scatter
:1748-1852) re-expressed the TPU way: `make_async_remote_copy` plays the
rendezvous one-sided RDMA WRITE (rdma_sq_handler.cpp:53-130), DMA
semaphores play the WR_DONE / address-exchange completions, and the
neighbor barrier plays session setup.  Double-buffered communication
slots give the 2-deep software pipelining the firmware gets from its
`end_move` windows.

All entry points must be called inside `shard_map` over a 1-D mesh axis
(ICI ring).  Chunk sizes must fit VMEM (~16 MB/core): callers segment
larger payloads exactly as the firmware segments to rx-buffer size.

On non-TPU platforms the kernels run under the Pallas TPU interpreter
(`interpret=True` → `pltpu.InterpretParams`) which simulates the remote
DMAs — the CPU rung of the test ladder.

Device tracing (r15): with ``ACCL_DEVICE_TRACE`` set, every ring
kernel writes one stamp row per step — logical phase stamps
(send-issue, recv/ack-wait done, reduce/copy done; Pallas exposes no
cycle counter, so stamps are event-order clocks) plus the two ring
neighbors and per-neighbor byte counts — into an extra kernel output
that a ``jax.debug.callback`` lands in the trace collector
(observability/trace.py ``device:<collective>`` Perfetto tracks).  The
env gate is read ONCE at first kernel build; with it unset the built
kernels are bit-identical to the uninstrumented ones (no extra output,
no callback — the jaxpr pin in tests/test_device_trace.py).
"""
from __future__ import annotations

import functools
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..observability.trace import DEVICE_TRACE_FIELDS, record_device_steps
from ..utils.compat import axis_size as _axis_size
from ..utils.compat import tpu_compiler_params as _tpu_compiler_params

#: stamp-row width of the ACCL_DEVICE_TRACE kernel output (the column
#: schema lives with its consumer: observability/trace.py)
DEVICE_TRACE_COLS = len(DEVICE_TRACE_FIELDS)

#: env gate, read once at first kernel build (None = not read yet) —
#: the off path must cost zero structurally, so the gate can never be
#: consulted per call beyond one module-global read
_DEVICE_TRACE: Optional[bool] = None


def device_trace_enabled() -> bool:
    """The ``ACCL_DEVICE_TRACE`` gate, cached at first kernel build."""
    global _DEVICE_TRACE
    if _DEVICE_TRACE is None:
        _DEVICE_TRACE = os.environ.get(
            "ACCL_DEVICE_TRACE", "0") not in ("", "0")
    return bool(_DEVICE_TRACE)


def _reset_device_trace_cache() -> None:
    """Test hook: force the next kernel build to re-read the env."""
    global _DEVICE_TRACE
    _DEVICE_TRACE = None


def _emit_device_trace(collective: str, buf: Any) -> None:
    """Arm the host callback that lands one stamp buffer in the trace
    collector (runs at execution time with the concrete array, inside
    jit/shard_map)."""
    jax.debug.callback(
        functools.partial(record_device_steps, collective), buf)


def _stamp_row(trace_ref: Any, step: int, my: Any, tx_peer: Any,
               rx_peer: Any, tx_bytes: int, rx_bytes: int) -> None:
    """Write one per-step stamp row (DEVICE_TRACE_FIELDS order).  The
    three phase stamps are the logical event clock 3*step + {0,1,2}:
    send-issue, recv/ack-wait done, reduce/copy done."""
    seq = 3 * step
    trace_ref[step, :] = jnp.stack([
        jnp.asarray(my, jnp.int32),
        jnp.int32(step),
        jnp.int32(seq),
        jnp.int32(seq + 1),
        jnp.int32(seq + 2),
        jnp.asarray(tx_peer, jnp.int32),
        jnp.asarray(rx_peer, jnp.int32),
        jnp.int32(tx_bytes),
        jnp.int32(rx_bytes),
    ])


def _payload_nbytes(shape: tuple, dtype: Any) -> int:
    """Bytes of one chunk of `shape`/`dtype` — the per-hop tx/rx byte
    count the stamp rows carry (a Python int at kernel-build time)."""
    n = int(np.dtype(dtype).itemsize)
    for d in shape:
        n *= int(d)
    return n


def _interp(interpret: bool):
    if not interpret:
        return False
    from jax.experimental.pallas import tpu as pltpu

    try:
        return pltpu.InterpretParams()
    except Exception:
        return True


# ---------------------------------------------------------------------------
# Flow-control window algebra — shared by the kernels below and by the
# discrete-event replay in tests/test_ring_flowcontrol.py, which runs
# the schedule under adversarial delivery and fails on any off-by-one
# (double-buffer overrun, deadlock, or semaphore-ledger leak) BEFORE it
# can deadlock real hardware.  The CPU interpreter serializes
# rdma.start();rdma.wait() and can never provoke these races itself.
#
# All-gather: comm slot parity flips every step; the slot we will land
# the NEXT incoming chunk in was last read by our own forwarding send
# one step ago, so from step 1 on we must hold the left neighbor off
# until we ACK, and we ACK a slot as soon as our send out of it
# completes — except the last two steps, whose slots are never written
# again (fw RAW hazard :1457-1460).
def ag_waits_ack(step: int, P: int) -> bool:
    return step >= 1


def ag_signals_ack(step: int, P: int) -> bool:
    return step <= P - 3


# Reduce-scatter: the landing buffer (not the accumulator) is double-
# buffered; a slot is reusable after the fold that consumed it, two
# steps after it was written.
def rs_waits_ack(step: int, P: int) -> bool:
    return step >= 2


def rs_signals_ack(step: int, P: int) -> bool:
    return step <= P - 4


def ring_all_gather_pallas(x, axis: str = "rank", interpret: bool = False,
                           collective_id: int = 0,
                           ring_size: int | None = None):
    """All-gather over a ring: per-member [n, ...] → [P, n, ...].

    Pattern: local slot write, then P-1 hops; each hop remote-copies the
    newest chunk to the right neighbor's double-buffered landing slot
    (the guide's canonical ring; fw eager allgather relay :1404-1502).

    ``ring_size`` (only with a 1-member axis) runs the kernel as a
    VIRTUAL V-rank self-ring on the single device: every hop is a real
    remote DMA (device_id = self) with the real semaphore handshakes
    and ACK-window flow control, so the compiled collective executes on
    one chip — the reference's run-the-synthesized-artifact rung
    (test/model/simulator/cclo_sim.cpp:57-559).  Since every virtual
    rank is this device, the result is x tiled V times (checkable).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    P = _axis_size(axis)
    V = ring_size if ring_size is not None else P
    if V != P and P != 1:
        raise ValueError("ring_size override requires a 1-member axis "
                         f"(self-ring mode); got P={P}, ring_size={V}")
    if V == 1:
        return x[None]
    devtrace = device_trace_enabled()
    chunk_bytes = _payload_nbytes(x.shape, x.dtype)

    def kernel(x_ref, out_ref, *rest):
        if devtrace:
            trace_ref, comm_buf, send_sem, recv_sem, ack_sem, copy_sem \
                = rest
        else:
            comm_buf, send_sem, recv_sem, ack_sem, copy_sem = rest
        my = lax.axis_index(axis) % V
        right = (my + 1) % P

        # neighbor handshake so nobody's landing slot is written before
        # the kernel owns it (session-setup equivalent)
        barrier = pltpu.get_barrier_semaphore()
        left = (my + P - 1) % P
        pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

        # our own block: out[my] and the first send slot
        local_out = pltpu.make_async_copy(x_ref, out_ref.at[my], copy_sem)
        local_out.start()
        local_comm = pltpu.make_async_copy(x_ref, comm_buf.at[0], copy_sem)
        local_comm.start()
        local_out.wait()
        local_comm.wait()

        for step in range(V - 1):
            slot = step % 2
            nxt = (step + 1) % 2
            # flow control: the slot we are about to write on the right
            # neighbor was freed by its own send two steps ago — wait for
            # its consumption ACK so a fast ring segment can't overrun the
            # double buffer (the firmware's rx-buffer RAW hazard,
            # fw :1457-1460, solved with sequence windows there)
            if ag_waits_ack(step, V):
                pltpu.semaphore_wait(ack_sem.at[nxt], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[slot],
                dst_ref=comm_buf.at[nxt],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[nxt],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdma.wait()
            # our send of comm_buf[slot] is complete: that slot is free
            # for the left neighbor's next write into it
            if ag_signals_ack(step, V):
                pltpu.semaphore_signal(
                    ack_sem.at[slot], inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
            origin = (my - step - 1) % V
            put = pltpu.make_async_copy(comm_buf.at[nxt], out_ref.at[origin],
                                        copy_sem)
            put.start()
            put.wait()
            if devtrace:
                # per-step stamp row: each hop relays one chunk to the
                # right neighbor and lands one from the left
                _stamp_row(trace_ref, step, my, right, left,
                           chunk_bytes, chunk_bytes)

    out_shape: Any = jax.ShapeDtypeStruct((V,) + x.shape, x.dtype)
    out_specs: Any = pl.BlockSpec(memory_space=pl.ANY)
    if devtrace:
        out_shape = [out_shape, jax.ShapeDtypeStruct(
            (V - 1, DEVICE_TRACE_COLS), jnp.int32)]
        out_specs = [out_specs, pl.BlockSpec(memory_space=pltpu.SMEM)]
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((2,) + x.shape, x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=_tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id),
        interpret=_interp(interpret),
    )(x)
    if devtrace:
        out, tr = res
        _emit_device_trace("all_gather", tr)
        return out
    return res


def ring_reduce_scatter_pallas(x, axis: str = "rank", op: str = "sum",
                               interpret: bool = False,
                               collective_id: int = 1,
                               ring_size: int | None = None):
    """Ring reduce-scatter: per-member [P, n, ...] → member's reduced
    [n, ...] (fw :1782-1850: send chunk (rank-1), P-2 fused
    recv+reduce+forward hops, final hop folds chunk `rank`).

    ``ring_size`` (1-member axis only): virtual V-rank self-ring on one
    device — real remote DMAs and semaphore flow control, every virtual
    rank being this device (see ring_all_gather_pallas).  The self-ring
    result is the full `op`-reduction of our own V chunks (each hop's
    incoming partial is our own accumulator)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    P = _axis_size(axis)
    V = ring_size if ring_size is not None else P
    if V != P and P != 1:
        raise ValueError("ring_size override requires a 1-member axis "
                         f"(self-ring mode); got P={P}, ring_size={V}")
    if V == 1:
        return x[0]
    chunk_shape = x.shape[1:]
    is_max = op == "max"
    devtrace = device_trace_enabled()
    chunk_bytes = _payload_nbytes(chunk_shape, x.dtype)

    def kernel(x_ref, out_ref, *rest):
        if devtrace:
            trace_ref, acc, landing, send_sem, recv_sem, ack_sem, \
                copy_sem = rest
        else:
            acc, landing, send_sem, recv_sem, ack_sem, copy_sem = rest
        my = lax.axis_index(axis) % V
        right = ((my + 1) % V) % P
        left = ((my + V - 1) % V) % P

        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

        # acc starts as our chunk (my - 1): the first payload forwarded
        first = (my + V - 1) % V
        ld = pltpu.make_async_copy(x_ref.at[first], acc, copy_sem)
        ld.start()
        ld.wait()

        for step in range(V - 1):
            slot = step % 2
            # flow control: the landing slot we target was consumed by
            # the right neighbor's fold two steps ago — wait for its ACK
            # so ring skew can't overrun the double buffer
            if rs_waits_ack(step, V):
                pltpu.semaphore_wait(ack_sem.at[slot], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=acc,
                dst_ref=landing.at[slot],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdma.wait()
            # fold the arriving partial with our local copy of the chunk
            # now travelling: chunk (my - 2 - step) mod V
            cidx = (my - 2 - step) % V
            ld2 = pltpu.make_async_copy(x_ref.at[cidx], acc, copy_sem)
            ld2.start()
            ld2.wait()
            if is_max:
                acc[...] = jnp.maximum(acc[...], landing[slot])
            else:
                acc[...] = acc[...] + landing[slot]
            # landing[slot] consumed: free it for the left neighbor's
            # write at its step (step + 2)
            if rs_signals_ack(step, V):
                pltpu.semaphore_signal(
                    ack_sem.at[slot], inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
            if devtrace:
                # per-step stamp row: one partial forwarded right, one
                # landed from the left and folded into the accumulator
                _stamp_row(trace_ref, step, my, right, left,
                           chunk_bytes, chunk_bytes)

        st = pltpu.make_async_copy(acc, out_ref, copy_sem)
        st.start()
        st.wait()

    out_shape: Any = jax.ShapeDtypeStruct(chunk_shape, x.dtype)
    out_specs: Any = pl.BlockSpec(memory_space=pl.ANY)
    if devtrace:
        out_shape = [out_shape, jax.ShapeDtypeStruct(
            (V - 1, DEVICE_TRACE_COLS), jnp.int32)]
        out_specs = [out_specs, pl.BlockSpec(memory_space=pltpu.SMEM)]
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM(chunk_shape, x.dtype),
            pltpu.VMEM((2,) + chunk_shape, x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=_tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id),
        interpret=_interp(interpret),
    )(x)
    if devtrace:
        out, tr = res
        _emit_device_trace("reduce_scatter", tr)
        return out
    return res


def ring_all_reduce_pallas(x, axis: str = "rank", op: str = "sum",
                           interpret: bool = False, cid_rs: int = 1,
                           cid_ag: int = 0, ring_size: int | None = None):
    """Segmented ring allreduce = ring reduce-scatter + ring all-gather
    (fw :1888-2071).  Per-member x: [P * n, ...] → same shape, reduced.

    The two phases reuse the ring kernels; XLA overlaps the phase
    boundary across segments when callers loop over segments.
    ``ring_size`` propagates the single-device virtual self-ring mode
    (see ring_all_gather_pallas).
    """
    P = _axis_size(axis)
    V = ring_size if ring_size is not None else P
    if V != P and P != 1:
        raise ValueError("ring_size override requires a 1-member axis "
                         f"(self-ring mode); got P={P}, ring_size={V}")
    if V == 1:
        return x
    n = x.shape[0] // V
    chunks = x.reshape((V, n) + x.shape[1:])
    mine = ring_reduce_scatter_pallas(chunks, axis, op=op,
                                      interpret=interpret,
                                      collective_id=cid_rs,
                                      ring_size=ring_size)
    gathered = ring_all_gather_pallas(mine, axis, interpret=interpret,
                                      collective_id=cid_ag,
                                      ring_size=ring_size)
    return gathered.reshape(x.shape)


# ---------------------------------------------------------------------------
# segmentation drivers — the firmware's rx-buffer segmentation above the
# ring kernels (fw :1888-2071: chunk to rx-buf size, bulk/tail split for
# ragged payloads).  Chunks are sized to fit VMEM; the Python segment
# loop unrolls under jit, and alternating collective_id pairs per
# segment parity keep consecutive segments' barrier semaphores distinct
# so XLA may overlap them (the firmware's 2-deep end_move window).
# ---------------------------------------------------------------------------

#: default segment length in ELEMENTS of the flat payload (1 MiB fp32);
#: each ring chunk is seg/P elements — comfortably inside ~16 MB VMEM
#: with the double-buffered landing slots
DEFAULT_SEG_ELEMS = 1 << 18


def _pad_to(x, length):
    if x.shape[0] == length:
        return x
    pad = jnp.zeros((length - x.shape[0],) + x.shape[1:], x.dtype)
    return jnp.concatenate([x, pad])


def ring_all_reduce_segmented(x, axis: str = "rank", op: str = "sum",
                              seg_elems: int = DEFAULT_SEG_ELEMS,
                              interpret: bool = False):
    """Flat per-member [N] → [N] allreduced, segmented through the ring
    kernels.  Handles ragged tails by padding the last segment up to a
    multiple of the ring size (the firmware's bulk/tail counts,
    fw :1909-1912)."""
    P = _axis_size(axis)
    if P == 1:
        return x
    N = x.shape[0]
    seg = max(P, (min(seg_elems, N) // P) * P)
    outs = []
    off = 0
    i = 0
    while off < N:
        s = min(seg, N - off)
        xs = x[off:off + s]
        padded = _pad_to(xs, -(-s // P) * P)
        cid = 2 * (i % 2)
        red = ring_all_reduce_pallas(padded, axis, op=op,
                                     interpret=interpret,
                                     cid_rs=cid, cid_ag=cid + 1)
        outs.append(red[:s])
        off += s
        i += 1
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs)


def ring_all_gather_segmented(x, axis: str = "rank",
                              seg_elems: int = DEFAULT_SEG_ELEMS,
                              interpret: bool = False):
    """Flat per-member [n] → [P * n] (rank-major), segmented.  Each
    segment gathers to [P, s]; blocks are re-interleaved so the final
    layout matches one whole-payload all-gather."""
    P = _axis_size(axis)
    if P == 1:
        return x
    n = x.shape[0]
    seg = min(seg_elems, n)
    pieces = []  # list of [P, s_i]
    off = 0
    i = 0
    while off < n:
        s = min(seg, n - off)
        g = ring_all_gather_pallas(x[off:off + s], axis,
                                   interpret=interpret,
                                   collective_id=i % 2)
        pieces.append(g)
        off += s
        i += 1
    if len(pieces) == 1:
        return pieces[0].reshape(-1)
    return jnp.concatenate(pieces, axis=1).reshape(-1)


def ring_reduce_scatter_segmented(x, axis: str = "rank", op: str = "sum",
                                  seg_elems: int = DEFAULT_SEG_ELEMS,
                                  interpret: bool = False):
    """Flat per-member [P * n] (rank-major) → member's reduced [n],
    segmented along the per-rank chunk dimension."""
    P = _axis_size(axis)
    if P == 1:
        return x
    n = x.shape[0] // P
    chunks = x.reshape(P, n)
    seg = min(seg_elems, n)
    outs = []
    off = 0
    i = 0
    while off < n:
        s = min(seg, n - off)
        r = ring_reduce_scatter_pallas(chunks[:, off:off + s], axis, op=op,
                                       interpret=interpret,
                                       collective_id=i % 2)
        outs.append(r)
        off += s
        i += 1
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs)
