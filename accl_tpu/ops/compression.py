"""Wire-compression lanes as Pallas TPU kernels.

Equivalent of the reference hp_compression plugin — streaming fp32↔fp16
casts at a 2:1 width ratio, instantiated three times for the op0/op1/res
lanes (kernels/plugins/hp_compression/hp_compression.cpp:70-144;
emulator wiring cclo_emu.cpp:396-399).  The TPU build generalizes the
target to {float16, bfloat16} (bf16 is the native TPU half type) and
adds optional stochastic rounding via the on-core PRNG — the technique
EQuARX-style quantized allreduce uses to stop bias accumulating over
ring hops (PAPERS.md).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils.compat import tpu_compiler_params as _tpu_compiler_params

# On-chip sweep (scripts/kernel_tune.py compress, 64 Mi f32 roundtrip,
# in-jit chained interleaved-window methodology): 512-lane rows dominate
# every other width by >2x, and 1024-row (2 MB) blocks edge out 256-row
# in shared windows, landing at/above the barriered XLA convert-pair
# ceiling measured in the same run.
_BLOCK_ROWS = 1024
_LANES = 512


def _cast_kernel(dtype):
    def kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:].astype(dtype)

    return kernel


def _stochastic_kernel(dtype):
    def kernel(seed_ref, x_ref, o_ref):
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        # fold the grid position into the seed: every block would
        # otherwise draw the SAME bit pattern and the rounding noise
        # would correlate block-to-block instead of averaging out
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
        o_ref[:] = pltpu.stochastic_round(x_ref[:], bits, target_dtype=dtype)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("dtype", "stochastic", "interpret"))
def _cast_2d(x, seed, dtype, stochastic: bool, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, cols = x.shape
    block_rows = min(_BLOCK_ROWS, rows)
    grid = (pl.cdiv(rows, block_rows),)
    spec = pl.BlockSpec((block_rows, cols), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct(x.shape, dtype)
    # every block is independent: parallel semantics let Mosaic overlap
    # the next block's DMA with the current cast
    params = _tpu_compiler_params(dimension_semantics=("parallel",))
    if stochastic:
        # scalar-prefetch index maps receive the prefetch ref as a
        # trailing argument — the specs need their own index lambdas
        pspec = pl.BlockSpec((block_rows, cols), lambda i, *_: (i, 0),
                             memory_space=pltpu.VMEM)
        return pl.pallas_call(
            _stochastic_kernel(dtype),
            out_shape=out_shape,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=grid,
                in_specs=[pspec],
                out_specs=pspec,
            ),
            compiler_params=params,
            interpret=interpret,
        )(seed, x)
    return pl.pallas_call(
        _cast_kernel(dtype),
        out_shape=out_shape,
        grid=grid,
        in_specs=[spec],
        out_specs=spec,
        compiler_params=params,
        interpret=interpret,
    )(x)


def _tiles(x):
    n = x.size
    flat = x.reshape(-1)
    rows = -(-n // _LANES)
    pad = rows * _LANES - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, x.dtype)])
    return flat.reshape(rows, _LANES), n


# The public lanes are jitted whole — pad/reshape/kernel/unpad fuse into
# ONE dispatch.  Unjitted, each call costs ~4 extra host round-trips for
# the reshapes, which dominates on remote-tunneled devices (measured
# 31 GB/s vs ~700 GB/s for the same kernel, scripts/kernel_tune.py).
@functools.partial(jax.jit,
                   static_argnames=("dtype", "stochastic", "interpret"))
def compress_cast(x, dtype=jnp.bfloat16, stochastic: bool = False,
                  seed: int = 0, interpret: bool = False):
    """Compress lane (hp_compression TDEST 0): fp32 → fp16/bf16.

    `stochastic=True` rounds with PRNG bits instead of
    round-to-nearest-even (TPU-only; requires the Mosaic PRNG).  `seed`
    is traced, so stepping it per call (to decorrelate ring hops) does
    NOT retrace."""
    x2, n = _tiles(x)
    seed_arr = jnp.reshape(jnp.asarray(seed, jnp.int32), (1,))
    out = _cast_2d(x2, seed_arr, jnp.dtype(dtype), stochastic, interpret)
    return out.reshape(-1)[:n].reshape(x.shape)


@functools.partial(jax.jit, static_argnames=("dtype", "interpret"))
def decompress_cast(x, dtype=jnp.float32, interpret: bool = False):
    """Decompress lane (hp_compression TDEST 1): fp16/bf16 → fp32."""
    x2, n = _tiles(x)
    out = _cast_2d(x2, jnp.array([0], jnp.int32), jnp.dtype(dtype), False,
                   interpret)
    return out.reshape(-1)[:n].reshape(x.shape)
