"""Wire-compression lanes as Pallas TPU kernels.

Equivalent of the reference hp_compression plugin — streaming fp32↔fp16
casts at a 2:1 width ratio, instantiated three times for the op0/op1/res
lanes (kernels/plugins/hp_compression/hp_compression.cpp:70-144;
emulator wiring cclo_emu.cpp:396-399).  The TPU build generalizes the
target to {float16, bfloat16} (bf16 is the native TPU half type) and
adds optional stochastic rounding via the on-core PRNG — the technique
EQuARX-style quantized allreduce uses to stop bias accumulating over
ring hops (PAPERS.md).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_BLOCK_ROWS = 1024
_LANES = 128


def _cast_kernel(dtype):
    def kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:].astype(dtype)

    return kernel


def _stochastic_kernel(dtype):
    def kernel(seed_ref, x_ref, o_ref):
        from jax.experimental.pallas import tpu as pltpu

        pltpu.prng_seed(seed_ref[0])
        bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.uint32)
        o_ref[:] = pltpu.stochastic_round(x_ref[:], bits, target_dtype=dtype)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("dtype", "stochastic", "interpret"))
def _cast_2d(x, seed, dtype, stochastic: bool, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, cols = x.shape
    block_rows = min(_BLOCK_ROWS, rows)
    grid = (pl.cdiv(rows, block_rows),)
    spec = pl.BlockSpec((block_rows, cols), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct(x.shape, dtype)
    if stochastic:
        return pl.pallas_call(
            _stochastic_kernel(dtype),
            out_shape=out_shape,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=grid,
                in_specs=[spec],
                out_specs=spec,
            ),
            interpret=interpret,
        )(seed, x)
    return pl.pallas_call(
        _cast_kernel(dtype),
        out_shape=out_shape,
        grid=grid,
        in_specs=[spec],
        out_specs=spec,
        interpret=interpret,
    )(x)


def _tiles(x):
    n = x.size
    flat = x.reshape(-1)
    rows = -(-n // _LANES)
    pad = rows * _LANES - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, x.dtype)])
    return flat.reshape(rows, _LANES), n


def compress_cast(x, dtype=jnp.bfloat16, stochastic: bool = False,
                  seed: int = 0, interpret: bool = False):
    """Compress lane (hp_compression TDEST 0): fp32 → fp16/bf16.

    `stochastic=True` rounds with PRNG bits instead of
    round-to-nearest-even (TPU-only; requires the Mosaic PRNG)."""
    x2, n = _tiles(x)
    seed_arr = jnp.array([seed], jnp.int32)
    out = _cast_2d(x2, seed_arr, jnp.dtype(dtype), stochastic, interpret)
    return out.reshape(-1)[:n].reshape(x.shape)


def decompress_cast(x, dtype=jnp.float32, interpret: bool = False):
    """Decompress lane (hp_compression TDEST 1): fp16/bf16 → fp32."""
    x2, n = _tiles(x)
    out = _cast_2d(x2, jnp.array([0], jnp.int32), jnp.dtype(dtype), False,
                   interpret)
    return out.reshape(-1)[:n].reshape(x.shape)
