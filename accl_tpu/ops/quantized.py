"""Int8 block-scaled quantized collectives — the wire-compression
algebra extended one tier below fp16.

The reference's hp_compression plugin stops at fp32<->fp16 (2:1 on the
wire, hp_compression.cpp:70-144).  On TPU the same role generalizes to
4:1: payloads cross the ICI ring as int8 with one fp32 scale per
`block` elements (symmetric absmax scaling), accumulation stays fp32 —
the EQuARX-style quantized allreduce of PAPERS.md.  Everything here is
jnp-level inside shard_map: quantization is elementwise + a small
reduction, exactly what XLA fuses into the ppermute pipeline on its own
(no Pallas needed — don't hand-schedule what the compiler already
does).

Error model: one symmetric absmax quantization rounds to within
scale/2 = absmax/254 per element.  The ring reduce-scatter requantizes
the running partial each hop (P-1 hops), so worst-case error grows
linearly in P — the same bias the reference's fp16 wire accumulates
over its fused recv-reduce-send rings, two tiers sharper.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size as _axis_size

DEFAULT_BLOCK = 256


def _blocks(x, block: int):
    n = x.shape[0]
    rows = -(-n // block)
    pad = rows * block - n
    if pad:
        x = jnp.concatenate([x, jnp.zeros(pad, x.dtype)])
    return x.reshape(rows, block), n


def quantize_blockwise(x, block: int = DEFAULT_BLOCK, key=None):
    """Flat fp array -> (q int8 [rows, block], scale f32 [rows, 1], n).

    Symmetric per-block absmax scaling; all-zero blocks get scale 1 so
    dequantization is exact for them.  With ``key`` (a jax PRNG key)
    rounding is STOCHASTIC — floor(r + u), u ~ U[0,1) — the same
    bias-breaking role the Pallas compress lanes' on-core PRNG plays
    (ops/compression.py stochastic_round); callers fold the ring
    hop/rank into the key so hops decorrelate."""
    x2, n = _blocks(x.astype(jnp.float32), block)
    amax = jnp.max(jnp.abs(x2), axis=1, keepdims=True)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0)
    r = x2 / scale
    if key is not None:
        import jax

        u = jax.random.uniform(key, r.shape, jnp.float32)
        rounded = jnp.floor(r + u)
    else:
        rounded = jnp.round(r)
    q = jnp.clip(rounded, -127, 127).astype(jnp.int8)
    return q, scale, n


def dequantize_blockwise(q, scale, n: int):
    """Inverse of :func:`quantize_blockwise` -> flat f32 [n]."""
    return (q.astype(jnp.float32) * scale).reshape(-1)[:n]


def _hop_key(seed: int, axis: str, hop):
    """PRNG key decorrelated per (seed, rank, hop) for stochastic
    rounding inside the ring loop."""
    import jax

    key = jax.random.fold_in(jax.random.PRNGKey(seed),
                             lax.axis_index(axis))
    return jax.random.fold_in(key, hop)


def _ring_reduce_scatter_q(x, axis: str, block: int,
                           error_feedback: bool = False,
                           stochastic: bool = False, seed: int = 0):
    """Quantized ring reduce-scatter returning the WIRE-FORM carry
    (q, scale, n) of this member's reduced chunk — so the all-reduce can
    feed it straight into the gather phase without a dequant/requant
    round at the seam.

    ``error_feedback`` (EQuARX, arxiv 2506.17615): each hop's
    requantization error is carried into the NEXT hop's quantization
    input instead of being dropped, so per-hop bias dithers out instead
    of accumulating linearly in P.  ``stochastic`` rounds with PRNG
    bits per (rank, hop) — the jnp twin of the Pallas compress lanes'
    on-core stochastic_round."""
    size = _axis_size(axis)
    idx = lax.axis_index(axis)
    if x.shape[0] % size != 0:
        raise ValueError(
            f"quantized ring reduce-scatter needs x.shape[0] ({x.shape[0]}) "
            f"divisible by the '{axis}' axis size ({size}); pad the input "
            "(sync_gradients pads via _pad_to_multiple)")
    n = x.shape[0] // size
    chunks = x.astype(jnp.float32).reshape(size, n)

    x0 = chunks[(idx - 1) % size]
    q0, s0, _ = quantize_blockwise(
        x0, block, key=_hop_key(seed, axis, 0) if stochastic else None)
    err0 = (x0 - dequantize_blockwise(q0, s0, n)) if error_feedback \
        else jnp.zeros((n,), jnp.float32)
    fwd = [(i, (i + 1) % size) for i in range(size)]

    def step(s, carry):
        q, sc, err = carry
        q = lax.ppermute(q, axis, fwd)
        sc = lax.ppermute(sc, axis, fwd)
        acc = dequantize_blockwise(q, sc, n) + chunks[(idx - 2 - s) % size]
        if error_feedback:
            acc = acc + err
        qn, scn, _ = quantize_blockwise(
            acc, block,
            key=_hop_key(seed, axis, s + 1) if stochastic else None)
        if error_feedback:
            err = acc - dequantize_blockwise(qn, scn, n)
        return qn, scn, err

    q, sc, _err = lax.fori_loop(0, size - 1, step, (q0, s0, err0))
    return q, sc, n


def _ring_all_gather_q(q, sc, n: int, axis: str):
    """Ring all-gather of an already-quantized (q, scale) pair -> flat
    [P * n] f32 (rank-major); contributions are relayed in wire form and
    dequantized once at the end."""
    size = _axis_size(axis)
    idx = lax.axis_index(axis)
    fwd = [(i, (i + 1) % size) for i in range(size)]

    out_q = jnp.zeros((size,) + q.shape, q.dtype).at[idx].set(q)
    out_s = jnp.zeros((size,) + sc.shape, sc.dtype).at[idx].set(sc)

    def step(s, carry):
        oq, os, cq, cs = carry
        cq = lax.ppermute(cq, axis, fwd)
        cs = lax.ppermute(cs, axis, fwd)
        origin = (idx - 1 - s) % size
        return oq.at[origin].set(cq), os.at[origin].set(cs), cq, cs

    out_q, out_s, _, _ = lax.fori_loop(0, size - 1, step,
                                       (out_q, out_s, q, sc))
    deq = out_q.astype(jnp.float32) * out_s  # [P, rows, block]
    return deq.reshape(size, -1)[:, :n].reshape(-1)


def quantized_ring_reduce_scatter(x, axis: str = "rank",
                                  block: int = DEFAULT_BLOCK,
                                  error_feedback: bool = False,
                                  stochastic: bool = False,
                                  seed: int = 0):
    """Ring reduce-scatter whose wire traffic is int8 + per-block scales.

    `x`: flat [P * n] per member -> this member's reduced chunk [n] f32.
    Each hop sends the quantized running partial one hop forward; the
    receiver dequantizes, folds its own chunk in fp32, and requantizes —
    the fused recv-reduce-send of the firmware's ring (fw :1782-1850)
    with a 4:1 wire format.  ``error_feedback``/``stochastic``: see
    :func:`_ring_reduce_scatter_q`."""
    q, sc, n = _ring_reduce_scatter_q(x, axis, block, error_feedback,
                                      stochastic, seed)
    return dequantize_blockwise(q, sc, n)


def quantized_ring_all_gather(x, axis: str = "rank",
                              block: int = DEFAULT_BLOCK,
                              stochastic: bool = False, seed: int = 0):
    """Ring all-gather whose wire traffic is int8 + per-block scales.

    `x`: flat [n] f32 per member -> [P * n] f32 (rank-major).  Each
    member's contribution is quantized ONCE and relayed; the error is a
    single round-trip regardless of P."""
    q, sc, _ = quantize_blockwise(
        x.astype(jnp.float32), block,
        key=_hop_key(seed, axis, 0) if stochastic else None)
    return _ring_all_gather_q(q, sc, x.shape[0], axis)


def quantized_all_reduce(x, axis: str = "rank",
                         block: int = DEFAULT_BLOCK,
                         error_feedback: bool = False,
                         stochastic: bool = False, seed: int = 0):
    """Segmented ring allreduce with int8 wire traffic: quantized ring
    reduce-scatter + quantized ring all-gather (the fused schedule of fw
    :1888-2071 at 4:1 wire width).  `x`: flat [P * n] -> [P * n] f32.
    The reduce-scatter's wire-form carry feeds the gather directly — no
    dequant/requant round at the seam."""
    q, sc, n = _ring_reduce_scatter_q(x, axis, block, error_feedback,
                                      stochastic, seed)
    return _ring_all_gather_q(q, sc, n, axis)
