"""On-path reduction arithmetic as Pallas TPU kernels.

Equivalent of the reference reduce_ops plugin: a 512-bit-wide SIMD
elementwise unit whose TDEST selects one of 10 (dtype, sum|max) lanes
(kernels/plugins/reduce_ops/reduce_ops.cpp:31-107).  On TPU the VPU is
the SIMD unit: these kernels stream both operands HBM→VMEM in tiles,
combine on the VPU, and stream back — the sustained rate is HBM-bound,
versus the reference datapath's 64 B/cycle @ 250 MHz = 16 GB/s ceiling
(BASELINE.md).

Outside TPU (tests on the CPU mesh) the kernels run in Pallas interpret
mode via the `interpret=` knob; `reduce_lane` also exposes a plain-jnp
fallback used by backends that are already inside a jit.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# VPU tile: 8 sublanes x 128 lanes for f32; block several tiles deep to
# amortize grid overhead
_BLOCK_ROWS = 512
_LANES = 128


def _kernel_add(a_ref, b_ref, o_ref):
    o_ref[:] = a_ref[:] + b_ref[:]


def _kernel_max(a_ref, b_ref, o_ref):
    o_ref[:] = jnp.maximum(a_ref[:], b_ref[:])


@functools.partial(jax.jit,
                   static_argnames=("is_max", "interpret", "block_rows",
                                    "donate"))
def _pallas_combine_2d(a, b, is_max: bool = False, interpret: bool = False,
                       block_rows: int = 0, donate: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, cols = a.shape
    block_rows = min(block_rows or _BLOCK_ROWS, rows)
    grid = (pl.cdiv(rows, block_rows),)
    spec = pl.BlockSpec((block_rows, cols), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _kernel_max if is_max else _kernel_add,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        grid=grid,
        in_specs=[spec, spec],
        out_specs=spec,
        # donate=True: the result may reuse operand 0's buffer — free
        # when the op is INLINED in a larger jit and the operand dies
        # there (the chained accumulate pattern); as a STANDALONE call
        # the operand is a non-donatable jit parameter and XLA would
        # satisfy the must-alias with a full copy instead, so the alias
        # is opt-in
        input_output_aliases={0: 0} if donate else {},
        interpret=interpret,
    )(a, b)


def _to_tiles(x):
    """Flatten to [rows, 128] padding the tail; returns (2d, orig_len)."""
    n = x.size
    flat = x.reshape(-1)
    rows = -(-n // _LANES)
    pad = rows * _LANES - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, x.dtype)])
    return flat.reshape(rows, _LANES), n


@functools.partial(jax.jit,
                   static_argnames=("interpret", "block_rows", "donate"))
def pallas_add(a, b, interpret: bool = False, block_rows: int = 0,
               donate: bool = False):
    """Elementwise sum lane (reduce_ops TDEST 0/2/4/6/8).  Jitted end to
    end so the tiling reshapes are layout no-ops instead of device
    copies.  `block_rows` overrides the VMEM tile depth (bench autotune;
    0 = default).  `donate=True` lets the result alias operand 0 — use
    when calling inlined in a larger jit where `a` dies (the accumulate
    pattern); see _pallas_combine_2d."""
    a2, n = _to_tiles(a)
    b2, _ = _to_tiles(b)
    out = _pallas_combine_2d(a2, b2, is_max=False, interpret=interpret,
                             block_rows=block_rows, donate=donate)
    return out.reshape(-1)[:n].reshape(a.shape)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_max(a, b, interpret: bool = False):
    """Elementwise max lane (reduce_ops TDEST 1/3/5/7/9)."""
    a2, n = _to_tiles(a)
    b2, _ = _to_tiles(b)
    out = _pallas_combine_2d(a2, b2, is_max=True, interpret=interpret)
    return out.reshape(-1)[:n].reshape(a.shape)


def reduce_lane(a, b, op: str = "sum", use_pallas: bool = True,
                interpret: bool = False):
    """Dispatch by (dtype, op) like the reference TDEST selector.

    With `use_pallas=False` (e.g. when already inside a jitted SPMD
    program) the combine lowers to a plain XLA fusion instead.
    """
    if op not in ("sum", "max"):
        raise ValueError(f"unknown reduce op {op!r}")
    if not use_pallas:
        return a + b if op == "sum" else jnp.maximum(a, b)
    fn = pallas_add if op == "sum" else pallas_max
    return fn(a, b, interpret=interpret)
