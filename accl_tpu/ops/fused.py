"""Compute/communication fusion — the vadd_put pattern on TPU.

The reference demonstrates kernels streaming operands directly into the
collective engine without touching memory (vadd_put.cpp:23-86 + the
stream flags in the call ABI).  The TPU equivalent is a compute kernel
whose output feeds a collective inside one jitted program, letting XLA
overlap the MXU work with ICI traffic — the tensor-parallel matmul +
all-reduce is the canonical case.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax


def _matmul_kernel(x_ref, w_ref, o_ref):
    o_ref[:] = jnp.dot(x_ref[:], w_ref[:],
                       preferred_element_type=jnp.float32)


def pallas_matmul(x, w, block_m: int = 256, block_n: int = 256,
                  interpret: bool = False):
    """Tiled MXU matmul (the compute half of the fusion).  Shapes must be
    multiples of the MXU tile (128) for peak efficiency."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x.shape
    k2, n = w.shape
    assert k == k2
    bm, bn = min(block_m, m), min(block_n, n)
    grid = (pl.cdiv(m, bm), pl.cdiv(n, bn))
    return pl.pallas_call(
        _matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, bn), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=(m * k + k * n + m * n) * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x, w)


def fused_matmul_allreduce(x, w, axis: str = "tp", use_pallas: bool = True,
                           interpret: bool = False):
    """Tensor-parallel contraction: each member holds a K-shard of the
    weight; the partial products all-reduce over the `axis` ring.  Call
    inside shard_map; XLA overlaps the psum with the matmul tail."""
    partial_out = (pallas_matmul(x, w, interpret=interpret)
                   if use_pallas else
                   jnp.dot(x, w, preferred_element_type=jnp.float32))
    return lax.psum(partial_out, axis)
