"""Fused compute/communication — chunked, double-buffered ring pipelines
that hide wire time under the MXU (r18).

The reference demonstrates kernels streaming operands directly into the
collective engine without touching memory (vadd_put.cpp:23-86 + the
stream flags in the call ABI), and ACCL+ (arxiv 2312.11742) shows where
the headroom lives: overlap the transfer of chunk k+1 with the compute
consuming chunk k.  This module is that schedule on TPU, in three tiers:

1. ``chunked_ring_*`` — the driver's fused lane (``ACCL_FUSED=1`` /
   per-call ``fused=``).  The flat payload is split into C independent
   per-chunk ppermute chains; at every ring step all C permutes are
   issued before any fold, so XLA pipelines chunk k+1's wire hop under
   chunk k's reduce.  The fp32 fold order is exactly the Pallas ring's
   (``local + incoming``, chunk ``(my - 2 - step) % P`` at step ``step``)
   so the fused lane is BITWISE-identical to the unfused ring whenever
   the payload divides P*C.  With ``wire=(block, error_feedback)`` the
   r17 int8 quantize/dequantize runs INSIDE the same loop body — one
   requantize per hop per chunk, no separate whole-buffer pack/unpack
   pass, wire-form carry across the reduce-scatter/all-gather seam.

2. ``fused_matmul_allreduce(chunks=C)`` — allreduce-into-matmul: the
   ring reduce-scatter phase computes each local partial product
   just-in-time (the MXU produces the block being folded while the next
   block's ppermute is in flight), then the all-gather relays reduced
   product rows.  ``fused_expert_ffn`` is the same idea for the MoE
   all_to_all: the dispatch for capacity-chunk k+1 overlaps the expert
   FFN consuming chunk k.

3. ``fused_matmul_reduce_scatter_pallas`` — the hand-scheduled Pallas
   form: the per-hop partial matmul executes between ``rdma.start()``
   and ``rdma.wait()`` on the accumulator's remote copy, with the same
   double-buffered landing slots and ACK-window flow control as
   ops/ring.py.

Device tracing (r15): with ``ACCL_DEVICE_TRACE`` set the fused lanes
stamp one row per (step, chunk) slot using an OVERLAPPED logical clock —
slot i's transfer spans [2i, 2i+2] and its reduce spans [2i+2, 2i+4],
so xfer(i+1) exactly covers reduce(i), the way the pipelined schedule
executes.  The sequential ring's 3-phase clock (ops/ring.py
``_stamp_row``) has zero xfer/reduce overlap by construction, which is
what `attribution.device_overlap` and scripts/overlap_smoke.py compare.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.compat import axis_size as _axis_size
from ..utils.compat import tpu_compiler_params as _tpu_compiler_params
from .quantized import dequantize_blockwise, quantize_blockwise
from .quantized import DEFAULT_BLOCK
from .ring import (
    DEVICE_TRACE_COLS,
    _emit_device_trace,
    _payload_nbytes,
    _interp,
    device_trace_enabled,
    rs_signals_ack,
    rs_waits_ack,
)

#: default pipeline depth of the fused lane — chunks per ring step;
#: 2 is the minimum that overlaps, 4 amortizes the per-chunk dispatch
DEFAULT_FUSED_CHUNKS = 4

#: env override, read once (None = not read yet) — the fused lane is
#: opt-in, but its chunk count must still be stable across rebuilds so
#: plan replays compile the same jaxpr
_FUSED_CHUNKS: Optional[int] = None


def fused_chunks() -> int:
    """The ``ACCL_FUSED_CHUNKS`` pipeline depth, cached at first use."""
    global _FUSED_CHUNKS
    if _FUSED_CHUNKS is None:
        try:
            _FUSED_CHUNKS = max(1, int(os.environ.get(
                "ACCL_FUSED_CHUNKS", str(DEFAULT_FUSED_CHUNKS))))
        except ValueError:
            _FUSED_CHUNKS = DEFAULT_FUSED_CHUNKS
    return _FUSED_CHUNKS


def _reset_fused_chunks_cache() -> None:
    """Test hook: force the next call to re-read the env."""
    global _FUSED_CHUNKS
    _FUSED_CHUNKS = None


def _pick_chunks(n: int, requested: Optional[int]) -> int:
    """Largest chunk count <= requested that divides n (>=1)."""
    c = max(1, min(requested or fused_chunks(), n))
    while n % c:
        c -= 1
    return c


def _pad_flat(x, length: int):
    if x.shape[0] == length:
        return x
    return jnp.concatenate(
        [x, jnp.zeros((length - x.shape[0],) + x.shape[1:], x.dtype)])


def _fused_stamp_rows(P: int, C: int, idx, chunk_bytes: int,
                      slot0: int = 0):
    """One stamp row per (step, chunk) pipeline slot, DEVICE_TRACE_FIELDS
    order, with the overlapped clock: xfer(i) = [2i, 2i+2], reduce(i) =
    [2i+2, 2i+4] — slot i+1's wire hop covers slot i's fold.

    With C == 1 there is only one chain and nothing to pipeline
    against, so the rows carry the sequential 3-phase clock
    (ops/ring.py ``_stamp_row``): the device timeline then honestly
    reports zero xfer/reduce overlap — the A/B baseline
    ``attribution.device_overlap`` compares the fused lanes to."""
    steps = (P - 1) * C
    slots = slot0 + jnp.arange(steps, dtype=jnp.int32)
    my = jnp.broadcast_to(jnp.asarray(idx, jnp.int32), (steps,))
    right = (my + 1) % P
    left = (my + P - 1) % P
    nbytes = jnp.full((steps,), jnp.int32(chunk_bytes))
    if C == 1:
        send, wait, phase = 3 * slots, 3 * slots + 1, 3 * slots + 2
    else:
        send, wait, phase = 2 * slots, 2 * slots + 2, 2 * slots + 4
    return jnp.stack(
        [my, slots, send, wait, phase, right, left, nbytes, nbytes],
        axis=1)


def _wire_hop_bytes(m: int, block: int) -> int:
    """Per-hop wire bytes of one int8 sub-chunk: int8 payload + one fp32
    scale per block (quantize_blockwise pads m up to a block multiple)."""
    rows = -(-m // block)
    return rows * block + rows * 4


# ---------------------------------------------------------------------------
# tier 1: chunked ring collectives — the driver's fused lane
# ---------------------------------------------------------------------------
def _rs_chains_fp(view, axis: str, op: str, P: int, C: int, idx, fwd):
    """C parallel reduce-scatter chains over `view` [P, C, m]; returns
    the list of per-chunk reduced accumulators.  All C permutes of a
    step are issued before any fold — the pipeline XLA overlaps."""
    is_max = op == "max"
    accs = [view[(idx - 1) % P, c] for c in range(C)]
    for s in range(P - 1):
        landed = [lax.ppermute(a, axis, fwd) for a in accs]
        jc = (idx - 2 - s) % P
        if is_max:
            accs = [jnp.maximum(view[jc, c], landed[c]) for c in range(C)]
        else:
            # local + incoming: the Pallas ring's fold order
            # (ring_reduce_scatter_pallas acc[...] = acc + landing)
            accs = [view[jc, c] + landed[c] for c in range(C)]
    return accs


def _rs_chains_q(view, axis: str, P: int, C: int, m: int, idx, fwd,
                 block: int, error_feedback: bool):
    """C parallel QUANTIZED reduce-scatter chains: the r17 int8 wire
    algebra (ops/quantized.py _ring_reduce_scatter_q) with the
    quantize/dequantize folded into the per-chunk loop body — each hop
    requantizes one sub-chunk, never the whole buffer.  Returns the list
    of wire-form (q, scale) carries (the seam feed for the gather)."""
    carries = []
    for c in range(C):
        x0 = view[(idx - 1) % P, c]
        q0, s0, _ = quantize_blockwise(x0, block)
        e0 = (x0 - dequantize_blockwise(q0, s0, m)) if error_feedback \
            else None
        carries.append((q0, s0, e0))
    for s in range(P - 1):
        moved = [(lax.ppermute(q, axis, fwd), lax.ppermute(sc, axis, fwd))
                 for (q, sc, _e) in carries]
        jc = (idx - 2 - s) % P
        nxt = []
        for c in range(C):
            q, sc = moved[c]
            err = carries[c][2]
            acc = dequantize_blockwise(q, sc, m) + view[jc, c]
            if error_feedback:
                acc = acc + err
            qn, scn, _ = quantize_blockwise(acc, block)
            en = (acc - dequantize_blockwise(qn, scn, m)) \
                if error_feedback else None
            nxt.append((qn, scn, en))
        carries = nxt
    return [(q, sc) for (q, sc, _e) in carries]


def _ag_chains(parts, axis: str, P: int, idx, fwd):
    """C parallel all-gather chains: relay each per-chunk part [m?]
    around the ring; returns [P, C, ...] with origin-major placement."""
    C = len(parts)
    stacked = jnp.stack(parts)  # [C, ...]
    outs = jnp.zeros((P,) + stacked.shape, stacked.dtype).at[idx].set(
        stacked)
    carries = list(parts)
    for s in range(P - 1):
        carries = [lax.ppermute(cc, axis, fwd) for cc in carries]
        origin = (idx - 1 - s) % P
        for c in range(C):
            outs = outs.at[origin, c].set(carries[c])
    return outs


def chunked_ring_reduce_scatter(x, axis: str = "rank", op: str = "sum",
                                chunks: Optional[int] = None,
                                wire: Optional[tuple] = None,
                                collective: str = "fused_reduce_scatter"):
    """Flat per-member [P * n] -> this member's reduced [n], pipelined
    as C independent per-chunk ring chains.  fp32 fold order matches the
    Pallas ring bitwise; ``wire=(block, error_feedback)`` rides the r17
    int8 wire with per-hop requantization fused into the loop."""
    P = _axis_size(axis)
    if P == 1:
        return x
    N = x.shape[0]
    if N % P:
        raise ValueError(f"fused reduce-scatter needs x.shape[0] ({N}) "
                         f"divisible by the '{axis}' axis size ({P})")
    n = N // P
    C = _pick_chunks(n, chunks)
    m = n // C
    idx = lax.axis_index(axis)
    fwd = [(i, (i + 1) % P) for i in range(P)]
    if wire is not None:
        if op == "max":
            raise ValueError("int8 wire lane carries sums, not max")
        block, ef = wire
        view = x.astype(jnp.float32).reshape(P, C, m)
        carries = _rs_chains_q(view, axis, P, C, m, idx, fwd, block, ef)
        parts = [dequantize_blockwise(q, sc, m) for q, sc in carries]
        hop_bytes = _wire_hop_bytes(m, block)
    else:
        view = x.reshape(P, C, m)
        parts = _rs_chains_fp(view, axis, op, P, C, idx, fwd)
        hop_bytes = _payload_nbytes((m,), x.dtype)
    if device_trace_enabled():
        _emit_device_trace(collective,
                           _fused_stamp_rows(P, C, idx, hop_bytes))
    return parts[0] if C == 1 else jnp.concatenate(parts)


def chunked_ring_all_gather(x, axis: str = "rank",
                            chunks: Optional[int] = None,
                            wire: Optional[tuple] = None,
                            collective: str = "fused_all_gather"):
    """Flat per-member [n] -> [P * n] (rank-major), pipelined as C
    per-chunk relay chains.  Values are relayed unchanged (fp) or
    quantized ONCE and relayed in wire form (int8 lane) — a single
    round-trip error regardless of P, as in r17."""
    P = _axis_size(axis)
    if P == 1:
        return x
    n = x.shape[0]
    C = _pick_chunks(n, chunks)
    m = n // C
    idx = lax.axis_index(axis)
    fwd = [(i, (i + 1) % P) for i in range(P)]
    if wire is not None:
        block = wire[0]
        view = x.astype(jnp.float32).reshape(C, m)
        qs = [quantize_blockwise(view[c], block)[:2] for c in range(C)]
        out_q = _ag_chains([q for q, _ in qs], axis, P, idx, fwd)
        out_s = _ag_chains([s for _, s in qs], axis, P, idx, fwd)
        deq = out_q.astype(jnp.float32) * out_s  # [P, C, rows, block]
        out = deq.reshape(P, C, -1)[:, :, :m].reshape(-1)
        hop_bytes = _wire_hop_bytes(m, block)
    else:
        view = x.reshape(C, m)
        out = _ag_chains([view[c] for c in range(C)], axis, P, idx,
                         fwd).reshape(-1)
        hop_bytes = _payload_nbytes((m,), x.dtype)
    if device_trace_enabled():
        _emit_device_trace(collective,
                           _fused_stamp_rows(P, C, idx, hop_bytes))
    return out


def chunked_ring_all_reduce(x, axis: str = "rank", op: str = "sum",
                            chunks: Optional[int] = None,
                            wire: Optional[tuple] = None,
                            collective: str = "fused_allreduce"):
    """Flat per-member [N] -> [N] allreduced: chunked reduce-scatter
    feeding chunked all-gather.  Pads internally to a P*C multiple; on
    the int8 lane the wire-form carry crosses the phase seam without a
    dequant/requant round (r17 invariant, now per chunk)."""
    P = _axis_size(axis)
    if P == 1:
        return x
    N = x.shape[0]
    C = max(1, chunks or fused_chunks())
    padN = -(-N // (P * C)) * (P * C)
    xp = _pad_flat(x, padN)
    n = padN // P
    m = n // C
    idx = lax.axis_index(axis)
    fwd = [(i, (i + 1) % P) for i in range(P)]
    if wire is not None:
        if op == "max":
            raise ValueError("int8 wire lane carries sums, not max")
        block, ef = wire
        view = xp.astype(jnp.float32).reshape(P, C, m)
        carries = _rs_chains_q(view, axis, P, C, m, idx, fwd, block, ef)
        out_q = _ag_chains([q for q, _ in carries], axis, P, idx, fwd)
        out_s = _ag_chains([s for _, s in carries], axis, P, idx, fwd)
        deq = out_q.astype(jnp.float32) * out_s
        out = deq.reshape(P, C, -1)[:, :, :m].reshape(-1)[:N]
        out = out.astype(x.dtype) if jnp.issubdtype(x.dtype, jnp.floating) \
            else out
        hop_bytes = _wire_hop_bytes(m, block)
    else:
        view = xp.reshape(P, C, m)
        parts = _rs_chains_fp(view, axis, op, P, C, idx, fwd)
        out = _ag_chains(parts, axis, P, idx, fwd).reshape(-1)[:N]
        hop_bytes = _payload_nbytes((m,), x.dtype)
    if device_trace_enabled():
        rows = jnp.concatenate([
            _fused_stamp_rows(P, C, idx, hop_bytes, slot0=0),
            _fused_stamp_rows(P, C, idx, hop_bytes, slot0=(P - 1) * C),
        ])
        _emit_device_trace(collective, rows)
    return out


# ---------------------------------------------------------------------------
# tier 2: allreduce-into-matmul and MoE dispatch fusion
# ---------------------------------------------------------------------------
def _matmul_kernel(x_ref, w_ref, o_ref):
    o_ref[:] = jnp.dot(x_ref[:], w_ref[:],
                       preferred_element_type=jnp.float32)


def pallas_matmul(x, w, block_m: int = 256, block_n: int = 256,
                  interpret: bool = False):
    """Tiled MXU matmul (the compute half of the fusion).  Shapes must be
    multiples of the MXU tile (128) for peak efficiency."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x.shape
    k2, n = w.shape
    assert k == k2
    bm, bn = min(block_m, m), min(block_n, n)
    grid = (pl.cdiv(m, bm), pl.cdiv(n, bn))
    return pl.pallas_call(
        _matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, bn), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=(m * k + k * n + m * n) * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x, w)


def fused_matmul_allreduce(x, w, axis: str = "tp", use_pallas: bool = True,
                           interpret: bool = False,
                           chunks: Optional[int] = None):
    """Tensor-parallel contraction: each member holds a K-shard of the
    weight; the partial products all-reduce over the `axis` ring.

    With ``chunks=None`` (the default) this is the r2 form — one matmul
    and a psum, XLA overlapping the tail.  With ``chunks=C`` it becomes
    the pipelined allreduce-into-matmul: the reduce-scatter phase
    computes each local row-block partial JUST-IN-TIME (the MXU produces
    the block being folded while the next block's ppermute is in
    flight), then the all-gather relays the reduced product rows.  Rows
    are zero-padded to a P*C multiple internally; output is fp32 either
    way."""
    if chunks is None or chunks <= 1:
        partial_out = (pallas_matmul(x, w, interpret=interpret)
                       if use_pallas else
                       jnp.dot(x, w, preferred_element_type=jnp.float32))
        return lax.psum(partial_out, axis)

    P = _axis_size(axis)
    if P == 1:
        return (pallas_matmul(x, w, interpret=interpret) if use_pallas
                else jnp.dot(x, w, preferred_element_type=jnp.float32))
    M, K = x.shape
    N = w.shape[1]
    C = chunks
    padM = -(-M // (P * C)) * (P * C)
    xp = _pad_flat(x, padM)
    mrows = padM // (P * C)
    xv = xp.reshape(P, C, mrows, K)
    idx = lax.axis_index(axis)
    fwd = [(i, (i + 1) % P) for i in range(P)]

    def dot_block(a):
        if use_pallas:
            return pallas_matmul(a, w, interpret=interpret)
        return jnp.dot(a, w, preferred_element_type=jnp.float32)

    # reduce-scatter of the product, local partial computed per hop —
    # the ppermute for chunk k+1 is independent of chunk k's matmul+fold
    accs = [dot_block(xv[(idx - 1) % P, c]) for c in range(C)]
    for s in range(P - 1):
        landed = [lax.ppermute(a, axis, fwd) for a in accs]
        jc = (idx - 2 - s) % P
        accs = [dot_block(xv[jc, c]) + landed[c] for c in range(C)]
    out = _ag_chains(accs, axis, P, idx, fwd).reshape(padM, N)[:M]
    if device_trace_enabled():
        hop_bytes = mrows * N * 4
        rows = jnp.concatenate([
            _fused_stamp_rows(P, C, idx, hop_bytes, slot0=0),
            _fused_stamp_rows(P, C, idx, hop_bytes, slot0=(P - 1) * C),
        ])
        _emit_device_trace("fused_matmul_allreduce", rows)
    return out


def fused_expert_ffn(x, expert_idx, ffn: Callable, axis: str = "ep",
                     capacity: int = 0, chunks: Optional[int] = None):
    """Reduce-scatter-into-MoE-dispatch: route tokens to their expert and
    run the expert FFN with the capacity dimension split into C chunks,
    so the all_to_all for chunk k+1 is in flight while ``ffn`` consumes
    chunk k (and the return all_to_all for chunk k overlaps chunk k+1's
    FFN).  Same slotting/capacity semantics as
    parallel.strategies.expert_dispatch/expert_combine; ``ffn`` maps
    [T, D] -> [T, D] row-wise (the per-expert MLP)."""
    P = _axis_size(axis)
    N, D = x.shape
    cap = capacity or -(-N // P)
    C = _pick_chunks(cap, chunks)
    ck = cap // C
    onehot = jax.nn.one_hot(expert_idx, P, dtype=jnp.int32)  # [N, P]
    pos_in_expert = jnp.cumsum(onehot, axis=0) * onehot - 1
    slot = jnp.sum(pos_in_expert * onehot, axis=1)  # [N]
    keep = slot < cap
    buckets = jnp.zeros((P, cap, D), x.dtype)
    buckets = buckets.at[expert_idx, jnp.clip(slot, 0, cap - 1)].add(
        jnp.where(keep[:, None], x, 0.0))
    back_parts = []
    for c in range(C):
        b = lax.dynamic_slice_in_dim(buckets, c * ck, ck, axis=1)
        recv = lax.all_to_all(b, axis, split_axis=0, concat_axis=0,
                              tiled=False)  # [P, ck, D]
        y = ffn(recv.reshape(P * ck, D))
        back_parts.append(
            lax.all_to_all(y.reshape(P, ck, D), axis, split_axis=0,
                           concat_axis=0, tiled=False))
    back = jnp.concatenate(back_parts, axis=1)  # [P, cap, D]
    if device_trace_enabled():
        idx = lax.axis_index(axis)
        hop_bytes = _payload_nbytes((ck, D), x.dtype)
        _emit_device_trace(
            "fused_expert_ffn",
            _fused_stamp_rows(P, C, idx, hop_bytes))
    gathered = back[expert_idx, jnp.clip(slot, 0, cap - 1)]
    return jnp.where(keep[:, None], gathered, 0.0)


# ---------------------------------------------------------------------------
# tier 3: the hand-scheduled Pallas kernel — per-hop matmul between
# rdma.start() and rdma.wait() on the accumulator's remote copy
# ---------------------------------------------------------------------------
def fused_matmul_reduce_scatter_pallas(x, w, axis: str = "rank",
                                       interpret: bool = False,
                                       collective_id: int = 1):
    """Ring reduce-scatter of the partial products sum_r x_r @ w_r with
    the matmul INSIDE the ring loop: x [P, m, K] per member (P row-blocks
    of this member's activations against its K-shard w [K, N]); returns
    this member's reduced [m, N] product block.

    Schedule per hop (vs ring_reduce_scatter_pallas, which idles between
    ``rdma.start()`` and ``rdma.wait()``): start the accumulator's
    remote copy, compute the NEXT local partial on the MXU while the DMA
    flies, then wait and fold.  Same double-buffered landing slots and
    ACK-window flow control; stamp rows use the overlapped clock."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    P = _axis_size(axis)
    if P == 1:
        return jnp.dot(x[0], w, preferred_element_type=jnp.float32)
    V, m, K = x.shape
    if V != P:
        raise ValueError(f"x leading dim ({V}) must equal the '{axis}' "
                         f"axis size ({P})")
    N = w.shape[1]
    out_block = (m, N)
    devtrace = device_trace_enabled()
    chunk_bytes = _payload_nbytes(out_block, jnp.float32)

    def kernel(x_ref, w_ref, out_ref, *rest):
        if devtrace:
            trace_ref, wv, xa, acc, landing, send_sem, recv_sem, \
                ack_sem, copy_sem = rest
        else:
            wv, xa, acc, landing, send_sem, recv_sem, ack_sem, \
                copy_sem = rest
        my = lax.axis_index(axis)
        right = (my + 1) % P
        left = (my + P - 1) % P

        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier, 2)

        lw = pltpu.make_async_copy(w_ref, wv, copy_sem)
        lw.start()
        lw.wait()
        # acc starts as our partial for chunk (my - 1): the first
        # payload forwarded (ring_reduce_scatter_pallas's `first`)
        first = (my + P - 1) % P
        ld = pltpu.make_async_copy(x_ref.at[first], xa, copy_sem)
        ld.start()
        ld.wait()
        acc[...] = jnp.dot(xa[...], wv[...],
                           preferred_element_type=jnp.float32)

        for step in range(P - 1):
            slot = step % 2
            if rs_waits_ack(step, P):
                pltpu.semaphore_wait(ack_sem.at[slot], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=acc,
                dst_ref=landing.at[slot],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            # MXU work under the wire: the local partial for the chunk
            # about to be folded, computed while the DMA is in flight
            cidx = (my - 2 - step) % P
            ld2 = pltpu.make_async_copy(x_ref.at[cidx], xa, copy_sem)
            ld2.start()
            ld2.wait()
            prod = jnp.dot(xa[...], wv[...],
                           preferred_element_type=jnp.float32)
            rdma.wait()
            acc[...] = prod + landing[slot]
            if rs_signals_ack(step, P):
                pltpu.semaphore_signal(
                    ack_sem.at[slot], inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
            if devtrace:
                # overlapped clock: this hop's wire spans [2s, 2s+2],
                # its fold [2s+2, 2s+4] — xfer(s+1) covers reduce(s)
                trace_ref[step, :] = jnp.stack([
                    jnp.asarray(my, jnp.int32),
                    jnp.int32(step),
                    jnp.int32(2 * step),
                    jnp.int32(2 * step + 2),
                    jnp.int32(2 * step + 4),
                    jnp.asarray(right, jnp.int32),
                    jnp.asarray(left, jnp.int32),
                    jnp.int32(chunk_bytes),
                    jnp.int32(chunk_bytes),
                ])

        st = pltpu.make_async_copy(acc, out_ref, copy_sem)
        st.start()
        st.wait()

    out_shape: Any = jax.ShapeDtypeStruct(out_block, jnp.float32)
    out_specs: Any = pl.BlockSpec(memory_space=pl.ANY)
    if devtrace:
        out_shape = [out_shape, jax.ShapeDtypeStruct(
            (P - 1, DEVICE_TRACE_COLS), jnp.int32)]
        out_specs = [out_specs, pl.BlockSpec(memory_space=pltpu.SMEM)]
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((K, N), w.dtype),
            pltpu.VMEM((m, K), x.dtype),
            pltpu.VMEM(out_block, jnp.float32),
            pltpu.VMEM((2,) + out_block, jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=_tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id),
        interpret=_interp(interpret),
    )(x, w)
    if devtrace:
        out, tr = res
        _emit_device_trace("fused_matmul_reduce_scatter", tr)
        return out
    return res


def fused_matmul_allreduce_pallas(x, w, axis: str = "rank",
                                  interpret: bool = False):
    """Allreduce-into-matmul, Pallas form: allreduce(sum_r x @ w_r) for
    x [M, K] (M divisible by P) and K-shard w [K, N] — the fused
    reduce-scatter kernel computes and folds per-hop partials under the
    wire, then the ring all-gather relays the reduced product rows."""
    from .ring import ring_all_gather_pallas

    P = _axis_size(axis)
    M, K = x.shape
    if P == 1:
        return jnp.dot(x, w, preferred_element_type=jnp.float32)
    if M % P:
        raise ValueError(f"M ({M}) must divide the '{axis}' axis size "
                         f"({P}); pad the row dimension")
    m = M // P
    mine = fused_matmul_reduce_scatter_pallas(
        x.reshape(P, m, K), w, axis, interpret=interpret, collective_id=1)
    gathered = ring_all_gather_pallas(mine, axis, interpret=interpret,
                                      collective_id=0)
    return gathered.reshape(M, w.shape[1])
