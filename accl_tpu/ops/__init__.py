"""Pallas TPU kernels — the dataplane's compute lanes.

Reference plugin mapping (SURVEY §2.5):

- ``reduce_ops``   → reduce_ops.py: tiled VPU elementwise sum/max over
                     {f32,f64→f32,i32,i64,f16,bf16} (the 512-bit SIMD
                     reduce_ops plugin, reduce_ops.cpp:31-107)
- ``hp_compression`` → compression.py: fp32↔fp16/bf16 streaming cast
                     lanes incl. stochastic rounding
                     (hp_compression.cpp:70-144)
- eager/rendezvous ring schedules → ring.py: ring collectives over
                     `make_async_remote_copy` + semaphores (the firmware
                     ring schedules on ICI instead of the DMA-mover)
- ``vadd_put``     → fused.py: compute fused with a collective (the
                     PL-kernel compute/comm fusion example)
- flash.py         → tiled online-softmax attention (MXU-resident; the
                     local-compute half of the ring-attention pattern —
                     no reference analog, TPU-first addition)
"""

from .compression import compress_cast, decompress_cast  # noqa: F401
from .flash import flash_attention  # noqa: F401
from .fused import fused_matmul_allreduce  # noqa: F401
from .quantized import (  # noqa: F401
    dequantize_blockwise,
    quantize_blockwise,
    quantized_all_reduce,
    quantized_ring_all_gather,
    quantized_ring_reduce_scatter,
)
from .reduce_ops import pallas_add, pallas_max, reduce_lane  # noqa: F401
from .ring import (  # noqa: F401
    ring_all_gather_pallas,
    ring_all_reduce_pallas,
    ring_reduce_scatter_pallas,
)
