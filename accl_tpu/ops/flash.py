"""Flash attention as a Pallas TPU kernel.

The MXU-resident attention block for the model families: tiled
QK^T -> online-softmax -> PV with the running (max, denominator)
carried across K blocks, so the [Tq, Tk] score matrix never
materializes in HBM.  Both schedules also emit log-sum-exp statistics,
which is what lets distributed callers fold partial attentions.

This is the local-compute half of the long-context story: ring
attention (accl_tpu.parallel.ring_attention) rotates K/V shards around
the ICI ring — the reference's fused recv-reduce-send ring schedule
(ccl_offload_control.c:1404-1502, :718) — and each arriving block is
consumed by exactly this kernel's math, with the shard-level merge
using the lse outputs.

Two schedules share one online-softmax fold and one wrapper:
- resident: the whole K/V row pinned in VMEM per batch-head (fetched
  once; fastest while it fits),
- grid: K/V streamed per (q-block, k-block) grid cell (any T).
The wrapper auto-switches on K/V size; `kernel=` forces either.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils.compat import tpu_compiler_params as _tpu_compiler_params

NEG_INF = -1e30
_LOG2E = 1.4426950408889634  # log2(e)
_LN2 = 0.6931471805599453    # ln(2)


def _softmax_fold(q, kb, vb, acc, m_prev, l_prev, *, mask, mxu_dtype,
                  static_max=None):
    """One online-softmax block fold shared by BOTH kernel schedules —
    the numerically delicate part (shift clamp so fully-masked rows
    don't produce exp(+big), masked-p zeroing, alpha rescale of the
    running state) lives exactly once.

    The fold runs in the LOG2 domain: q arrives PRE-SCALED by
    log2(e)/sqrt(D) (one [bq, D] multiply replaces a [bq, bk] VPU pass
    per fold — the kernel is VPU-bound at D=64, so score-matrix passes
    are the budget), so scores are log2-scaled logits, probabilities are
    exp2(s - m), and the TRUE log-sum-exp is m*ln2 + ln(l) — `_finalize`
    converts.  `p` values are identical to the natural-base fold
    (exp2(log2e*(x - m_nat)) == exp(x - m_nat)), so acc/l match exactly.

    kb/vb: [bk, D] (mxu dtype); acc/m/l are f32 running state.  `mask`
    is None or (row0, col0, window) block offsets for the causal
    row >= col test, with `window` further restricting each row to its
    trailing `window` columns (None = unwindowed).
    Returns (acc', m', l').

    FUSED-DENOMINATOR mode (`l_prev is None`): vb carries an appended
    ones column and acc the matching accumulator column, so the row-sum
    of p rides the PV matmul on the MXU and the explicit `jnp.sum` VPU
    pass disappears — free where D pads to the same lane tile anyway
    (D=64 -> 65 both pad to 128).  Returns (acc', m', None).

    STATIC-MAX mode (`static_max` a float): probabilities are
    exp2(s - static_max) with NO running max — the max reduction, the
    shift clamp, the alpha rescale of acc/l and the masked-p re-zero
    all disappear from the VPU budget (the fold is VPU-bound at
    D=128: these passes are the measured ceiling).  Exact as long as
    scaled logits stay within f32 range of the pin (|s - static_max|
    < ~126; see flash_attention_packed docs).  m carries through
    untouched; _finalize receives m = static_max (dead rows
    NEG_INF)."""
    s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return _fold_consume(s, vb, acc, m_prev, l_prev, mask=mask,
                         mxu_dtype=mxu_dtype, static_max=static_max)


def _fold_consume(s, vb, acc, m_prev, l_prev, *, mask, mxu_dtype,
                  static_max=None):
    """The softmax/PV half of the fold, consuming a PRECOMPUTED score
    block `s` [bq, bk] (raw, unmasked).  Split out so the skewed
    schedule can issue block j+1's QK^T before consuming block j's
    scores — numerics identical to :func:`_softmax_fold`, which now
    delegates here."""
    block_q, block_k = s.shape
    masked = mask is not None
    if masked:
        row0, col0, window = mask
        rows = row0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = col0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        keep = rows >= cols
        if window is not None:
            # sliding window: row r attends cols (r-window, r]
            keep = keep & (rows - cols < window)
        s = jnp.where(keep, s, NEG_INF)
    if static_max is not None:
        # static pin (see _softmax_fold): exp2(NEG_INF - pin) flushes
        # to +0.0 in f32 — masked cells need no re-zero, dead rows
        # produce l = 0 (the _finalize guard)
        p = jnp.exp2(s - static_max)
        l_new = (None if l_prev is None
                 else l_prev + jnp.sum(p, axis=-1, keepdims=True))
        acc_new = acc + jax.lax.dot_general(
            p.astype(mxu_dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_prev, l_new
    m_blk = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    # fully-masked block rows keep m at NEG_INF; exp2(s - NEG_INF) would
    # be exp2(+big) — guard by clamping the shift
    shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp2(s - shift)                         # [bq, bk]
    if masked:
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0,
                      jnp.exp2(m_prev - shift))     # rescale of old state
    l_new = (None if l_prev is None
             else alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True))
    acc_new = acc * alpha + jax.lax.dot_general(
        p.astype(mxu_dtype), vb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return acc_new, m_new, l_new


def _finalize(acc, m, lsum, o_ref, lse_ref, row_off=None):
    """Write the normalized output and the lse statistics (shared by
    both schedules so the denom/dead-row guards stay identical).  `m` is
    a log2-domain running max (see _softmax_fold); the emitted lse is in
    NATURAL log units — the cross-shard merge contract.

    `row_off` selects a row range of the block to write (the q-tile
    interleaved schedule finalizes per sub-tile); offset stores are used
    rather than `.at[]` ref views because a view of the lse block slices
    its tile-padded minor dim, which Mosaic rejects."""
    from jax.experimental import pallas as pl

    denom = jnp.where(lsum == 0.0, 1.0, lsum)
    out = (acc / denom).astype(o_ref.dtype)
    dead = m <= NEG_INF / 2
    lse = jnp.where(dead, NEG_INF,
                    m * _LN2 + jnp.log(jnp.maximum(lsum, 1e-38)))
    # lse block is [bq, 1] — the trailing unit dim keeps it tile-aligned
    # for Mosaic (second-minor bq % 8 == 0, minor == full)
    if row_off is None:
        o_ref[0] = out
        lse_ref[0] = lse
    else:
        rows = acc.shape[0]
        o_ref[0, pl.ds(row_off, rows), :] = out
        lse_ref[0, pl.ds(row_off, rows), :] = lse


def _causal_block_bounds(iq, block_q, block_k, nk_total):
    """(n_past, n_live) k-block bounds for q-block `iq` under the
    causal mask: blocks [0, n_past) are strictly past (no mask work),
    [n_past, n_live) straddle the diagonal (masked), [n_live, nk) are
    strictly future (skipped).  Shared by every resident-style
    schedule so the bounds cannot desynchronize between kernels."""
    n_past = (iq * block_q) // block_k
    n_live = (iq * block_q + block_q + block_k - 1) // block_k
    return n_past, jnp.minimum(n_live, nk_total)


def _run_block_loops(body, carry, causal, iq, block_q, block_k,
                     nk_total):
    """Drive `body(j, carry, masked)` over the k-blocks: the causal
    split (unmasked past bulk, masked diagonal epilogue) or the full
    unmasked range.  One copy of the loop scaffolding for every
    resident-style schedule — the carry (including the skew schedule's
    prefetched score block) crosses the loop boundary intact."""
    from jax import lax as jlax

    if causal:
        n_past, n_live = _causal_block_bounds(iq, block_q, block_k,
                                              nk_total)
        carry = jlax.fori_loop(0, n_past,
                               lambda j, c: body(j, c, masked=False),
                               carry)
        return jlax.fori_loop(n_past, n_live,
                              lambda j, c: body(j, c, masked=True),
                              carry)
    return jlax.fori_loop(0, nk_total,
                          lambda j, c: body(j, c, masked=False), carry)


def _flash_kernel_grid(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s,
                       *, scale: float, causal: bool, block_q: int,
                       block_k: int, chunk_k: int, nk: int,
                       nk_total: int | None = None, mxu_dtype,
                       kv_resident: bool = False, q_tiles: int = 1,
                       window=None, static_max=None):
    """Streaming schedule: grid (bh, q_block, k_block); K/V blocks
    arrive per grid cell; the accumulator lives in VMEM scratch across
    the sequential k steps of one (bh, q_block) cell.  Each arriving
    block is folded as an unrolled run of chunk_k sub-folds so the MXU
    stays busy while the VPU runs the previous chunk's softmax (same
    pipelining rationale as the resident kernel).  q_tiles > 1 splits
    the q block into independent sub-tile chains whose folds interleave
    (see the resident kernel's docstring) — the long-context schedule's
    version of the same MXU/VPU overlap."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    j = pl.program_id(2)
    # with a sliding window the k grid dimension is BOUNDED: it spans
    # only the blocks a q block can see (O(window) of them), and the
    # K/V index maps fetch from the same shifted base — out-of-window
    # blocks are never DMA'd, not merely predicated off.  ik is the
    # REAL k-block index the liveness/mask math needs.
    ik = j + (_window_first_block(iq, block_q, block_k, window)
              if window is not None else 0)
    tq = block_q // q_tiles

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    # a causal k-block strictly in this q-block's future contributes
    # nothing — skip its whole body (roughly halves the MXU work).
    # Blocks strictly in the past need no mask at all; only the blocks
    # straddling the diagonal (or the window edge) pay the iota/where
    # lane work.  ONE liveness helper is shared with both backward
    # kernels so the schedules cannot desynchronize.
    live, diag = _grid_live_masked(iq, ik, block_q, block_k, causal,
                                   window)
    if window is not None and nk_total is not None:
        # phantom tail cells of the bounded span (clamped fetches past
        # the real k range) stay dead regardless of the mask algebra —
        # here causality already kills them, but the guard keeps the
        # invariant explicit and future-proof
        live = live & (ik < nk_total)
        diag = diag & live

    q = (q_ref[0] * scale).astype(mxu_dtype)  # pre-scale once per block
    qs = [q[t * tq:(t + 1) * tq] for t in range(q_tiles)]

    def body(masked: bool):
        carries = [(acc[pl.ds(t * tq, tq), :], m_s[pl.ds(t * tq, tq), :],
                    l_s[pl.ds(t * tq, tq), :]) for t in range(q_tiles)]
        for c in range(block_k // chunk_k):
            off = ik * block_k + c * chunk_k
            # kv_resident: the refs hold the WHOLE row (the index map is
            # pinned, so Pallas fetched it once per batch-head) and the
            # block offset is applied here instead of by the pipeline
            base = off if kv_resident else c * chunk_k
            kb = k_ref[0, pl.ds(base, chunk_k), :].astype(mxu_dtype)
            vb = v_ref[0, pl.ds(base, chunk_k), :].astype(mxu_dtype)
            carries = [
                _softmax_fold(qs[t], kb, vb, *carries[t],
                              mask=((iq * block_q + t * tq, off, window)
                                    if masked else None),
                              mxu_dtype=mxu_dtype,
                              static_max=static_max)
                for t in range(q_tiles)]
        for t, (a, m, lsum) in enumerate(carries):
            acc[pl.ds(t * tq, tq), :] = a
            m_s[pl.ds(t * tq, tq), :] = m
            l_s[pl.ds(t * tq, tq), :] = lsum

    if causal:
        @pl.when(diag)
        def _diag_body():
            body(masked=True)

        @pl.when(live & jnp.logical_not(diag))
        def _past_body():
            body(masked=False)
    else:
        body(masked=False)

    @pl.when(j == nk - 1)
    def _fin():
        if static_max is None:
            m_fin = m_s[:]
        else:
            # the m scratch was never updated (see _softmax_fold's
            # static mode): reconstruct the pin for live rows and
            # NEG_INF for fully-dead ones so _finalize's lse/dead-row
            # algebra stays shared
            m_fin = jnp.where(l_s[:] == 0.0, NEG_INF, static_max)
        _finalize(acc[:], m_fin, l_s[:], o_ref, lse_ref)


def _flash_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *scratch,
                           scale: float, causal: bool, block_q: int,
                           block_k: int, chunk_k: int, T: int, mxu_dtype,
                           q_tiles: int = 1, fuse_denom: bool = False,
                           static_max=None):
    """K/V-resident schedule: the whole K/V row for this batch-head sits
    in VMEM (fetched ONCE — the grid variant refetches it per q-block,
    which is the streaming bound at small-to-medium T).

    Three throughput tricks beyond the plain fold:
    - when the input dtype differs from the MXU dtype, K/V are cast ONCE
      per batch-head into VMEM scratch at the first q-block (the naive
      per-fold cast re-converts the same rows nq times — measured as a
      double-digit share of kernel time at D=128);
    - each block_k fold is an UNROLLED run of chunk_k sub-folds, so
      Mosaic can issue chunk c+1's independent QK^T matmul while the VPU
      works on chunk c's softmax — without this the MXU idles during
      every max/exp2/sum pass and the kernel tops out near 50% MXU;
    - q_tiles > 1 splits the q block into INDEPENDENT sub-tiles whose
      folds are interleaved in program order: tile A's softmax (VPU) has
      no data dependence on tile B's matmuls (MXU), so the scheduler can
      run them concurrently — at D=128 one softmax pass costs about as
      much VPU time as the fold's two matmuls cost MXU time, so a single
      dependence chain caps the kernel near 50% MXU no matter how well
      a lone chain pipelines."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    D = q_ref.shape[-1]
    nk_total = T // block_k
    n_chunks = block_k // chunk_k
    tq = block_q // q_tiles
    # pre-scaled independent q sub-tiles (see q_tiles note above)
    qs = [(q_ref[0, pl.ds(t * tq, tq), :] * scale).astype(mxu_dtype)
          for t in range(q_tiles)]

    if fuse_denom:
        # fused-denominator layout (see _softmax_fold): the
        # ones-extended V lives in scratch, built once per batch-head;
        # K joins it only when it needs a dtype cast — otherwise it is
        # read straight from its ref (review finding: an unconditional
        # K copy wasted a (T, D) VMEM buffer when dtypes already match)
        *k_scr, vb_s = scratch
        @pl.when(iq == 0)
        def _build_kv():
            if k_scr:
                k_scr[0][:] = k_ref[0].astype(mxu_dtype)
            vb_s[:] = jnp.concatenate(
                [v_ref[0].astype(mxu_dtype),
                 jnp.ones((T, 1), mxu_dtype)], axis=1)

        def kv_chunk(off):
            kb = (k_scr[0][pl.ds(off, chunk_k), :] if k_scr
                  else k_ref[0, pl.ds(off, chunk_k), :].astype(mxu_dtype))
            return kb, vb_s[pl.ds(off, chunk_k), :]
    elif scratch:
        kb_s, vb_s = scratch
        # grid order within one batch-head is sequential (the iq
        # dimension is marked "arbitrary"), so the cast done at the
        # first q-block is visible to the rest
        @pl.when(iq == 0)
        def _cast_kv():
            kb_s[:] = k_ref[0].astype(mxu_dtype)
            vb_s[:] = v_ref[0].astype(mxu_dtype)

        def kv_chunk(off):
            return (kb_s[pl.ds(off, chunk_k), :],
                    vb_s[pl.ds(off, chunk_k), :])
    else:
        # no scratch: cast PER CHUNK like the grid schedule, so
        # mxu_dtype always governs the matmul input format (a no-op
        # when the input already arrives in MXU dtype)
        def kv_chunk(off):
            return (k_ref[0, pl.ds(off, chunk_k), :].astype(mxu_dtype),
                    v_ref[0, pl.ds(off, chunk_k), :].astype(mxu_dtype))

    def step(j, carries, masked):
        # unrolled chunk run — `for c in range(...)` is static, letting
        # the compiler software-pipeline MXU against VPU across chunks
        # and across the independent q sub-tiles within one chunk
        for c in range(n_chunks):
            off = j * block_k + c * chunk_k
            kb, vb = kv_chunk(off)
            nxt = []
            for t in range(q_tiles):
                acc, m_prev, l_prev = carries[t]
                mask = ((iq * block_q + t * tq, off, None)
                        if masked else None)
                nxt.append(_softmax_fold(qs[t], kb, vb, acc, m_prev,
                                         l_prev, mask=mask,
                                         mxu_dtype=mxu_dtype,
                                         static_max=static_max))
            carries = tuple(nxt)
        return carries

    acc_d = D + 1 if fuse_denom else D
    carry = tuple((jnp.zeros((tq, acc_d), jnp.float32),
                   jnp.full((tq, 1), NEG_INF, jnp.float32),
                   None if fuse_denom else jnp.zeros((tq, 1), jnp.float32))
                  for _ in range(q_tiles))
    carry = _run_block_loops(step, carry, causal, iq, block_q,
                             block_k, nk_total)
    for t in range(q_tiles):
        acc, m, lsum = carry[t]
        if fuse_denom:
            acc, lsum = acc[:, :D], acc[:, D:]
        if static_max is not None:
            # the carry's m was never updated — reconstruct the value
            # _finalize's lse/dead-row algebra expects: the pin for
            # live rows, NEG_INF for fully-dead rows (l stayed 0)
            m = jnp.where(lsum == 0.0, NEG_INF, static_max)
        _finalize(acc, m, lsum, o_ref, lse_ref,
                  row_off=None if q_tiles == 1 else t * tq)


def _flash_kernel_resident_skew(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                                scale: float, causal: bool, block_q: int,
                                block_k: int, T: int, mxu_dtype):
    """Software-pipelined resident schedule (single fold chain): the
    QK^T for k-block j+1 is issued BEFORE block j's softmax/PV consume
    its scores, carrying the prefetched score block [bq, bk] through
    the fori_loop.  In the plain chain the next matmul depends on the
    fold's full VPU pass (via the alpha rescale), so the MXU idles
    through every max/exp2/sum; the skew makes the lookahead matmul
    data-independent of the current consume, exposing a legal MXU/VPU
    overlap window to the static scheduler instead of hoping it finds
    one inside a serialized body.  The lookahead at the last block
    clamps its read and is discarded.  Numerics are bit-identical to
    the plain resident chain (same _fold_consume, same fold order).

    MEASURED RESULT (honest-timing r04 sweeps): consistently SLOWER
    than the plain chain (0.21-0.22 vs 0.34-0.36 MXU fraction at
    D=128) — the [bq, bk] f32 score block carried through the
    fori_loop costs more VMEM traffic than the exposed overlap buys.
    Kept as a selectable schedule so the negative result stays
    reproducible (`kernel="resident_skew"`); not in the auto table."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    nk_total = T // block_k
    q = (q_ref[0] * scale).astype(mxu_dtype)

    def score(j):
        # clamp the lookahead read: at the final block this computes a
        # discarded extra score block against the last K rows
        off = jnp.minimum(j, nk_total - 1) * block_k
        kb = k_ref[0, pl.ds(off, block_k), :].astype(mxu_dtype)
        return jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    def body(j, carry, masked):
        acc, m, lsum, s_cur = carry
        # lookahead FIRST in program order — independent of the consume
        s_nxt = score(j + 1)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(mxu_dtype)
        mask = (iq * block_q, j * block_k, None) if masked else None
        acc, m, lsum = _fold_consume(s_cur, vb, acc, m, lsum, mask=mask,
                                  mxu_dtype=mxu_dtype)
        return acc, m, lsum, s_nxt

    D = q_ref.shape[-1]
    carry = (jnp.zeros((block_q, D), jnp.float32),
             jnp.full((block_q, 1), NEG_INF, jnp.float32),
             jnp.zeros((block_q, 1), jnp.float32),
             score(0))
    carry = _run_block_loops(body, carry, causal, iq, block_q,
                             block_k, nk_total)
    acc, m, lsum, _ = carry
    _finalize(acc, m, lsum, o_ref, lse_ref)


def _vma_of(*xs):
    """Join of the inputs' device-variance sets when tracing inside
    shard_map (None outside); pallas_call out_shapes must carry it."""
    vma = None
    for x in xs:
        v = getattr(getattr(x, "aval", None), "vma", None)
        if v:
            vma = v if vma is None else (vma | v)
    return vma


def _sds(shape, dtype, vma):
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


#: K/V rows larger than this stay on the streaming (grid) kernel; below
#: it both rows fit VMEM comfortably alongside the double-buffered q/o
#: blocks (~16 MB/core)
_RESIDENT_KV_BYTES = 6 << 20

#: Auto-schedule defaults applied when the caller leaves q_tiles=None
#: (the public default).  Tuned against the live-chip schedule sweep
#: (scripts/flash_tune.py / scripts/chip_session.py over
#: accl_tpu/bench/flash_sweep.py) under the min-RTT timing harness
#: (bench/timing.py — earlier sweeps banked an inflated sync estimate
#: and their numbers were unusable): across four honest windows at
#: D=128 the plain single chain and the two-chain q-tile interleave
#: are statistically tied (0.29-0.39 MXU fraction, ordering flips
#: window to window) while split folds (chunk_k < block_k) and qt4
#: consistently lose — so the auto table keeps the SIMPLEST schedule.
#: Explicit q_tiles/chunk_k always win over the auto table.
_AUTO_Q_TILES = 1
_AUTO_CHUNK_K = None  # None = fold whole K blocks (no sub-chunk split)


def _snap_chunk(req: int, blk: int) -> int:
    """Largest divisor of `blk` at or below `req`, never under the
    8-row tile floor (falls back to the whole block) — the one snapping
    rule for every sub-chunk unroll (forward folds and backward cells).
    """
    return next((d for d in range(min(req, blk), 7, -1)
                 if blk % d == 0), blk)


def _resolve_schedule(T, Tk, D, qdtype, causal, block_q, block_k,
                      interpret, mxu_dtype, kernel, chunk_k,
                      kv_cast_scratch, q_tiles, fuse_denom,
                      window=None, static_max=None):
    """Static schedule resolution shared by the head-packed and BTHD
    entries: block shrinking, chunk snapping, kernel/auto selection and
    the tuned-auto q_tiles/fuse_denom choices.  Returns the cfg tuple
    consumed by the forward/backward impls."""
    # shrink blocks (by halving, down to the 8-row f32 tile floor) until
    # they divide their sequence length, so defaults keep working for
    # any T smaller defaults accepted
    bq, bk = min(block_q, T), min(block_k, Tk)
    while T % bq != 0 and bq > 8:
        bq //= 2
    while Tk % bk != 0 and bk > 8:
        bk //= 2
    if T % bq != 0 or Tk % bk != 0:
        raise ValueError(
            f"sequence lengths {T}/{Tk} not divisible by blocks ({bq}, {bk})")
    # sub-fold chunk (None = whole block): smaller chunks give the
    # compiler MXU/VPU pipelining slack at the price of smaller matmuls.
    # Snap to the largest divisor of bk at or below the request, never
    # under the 8-row tile floor (halving alone can decay 12->3->1)
    ck = bk if chunk_k is None else _snap_chunk(chunk_k, bk)

    mxu_dtype = jnp.dtype(mxu_dtype)
    # one-shot K/V cast scratch is OPT-IN: it trades the per-fold cast
    # for a serialized q-block order ("arbitrary" semantics), a tradeoff
    # that must be measured per chip generation
    needs_cast = kv_cast_scratch and qdtype != mxu_dtype

    # q_tiles=None (the public default) opts into the auto schedule:
    # tuned (q_tiles, chunk_k) applied after the kernel resolves below.
    # Explicit q_tiles (incl. 1 = plain single-chain) is always honored.
    auto_sched = q_tiles is None
    if auto_sched:
        q_tiles = _AUTO_Q_TILES
    elif q_tiles < 1:
        raise ValueError(f"q_tiles={q_tiles} must be >= 1")
    # fuse_denom=None (the public default) is the auto choice, resolved
    # after the kernel lands below; explicit True/False always wins
    auto_fd = fuse_denom is None
    if not auto_fd and fuse_denom and kernel not in ("resident", "auto"):
        # an EXPLICIT non-resident kernel with the resident-only option
        # is a contradiction — silently not applying it would be a perf
        # lie.  (Under "auto" it is a tuning HINT and drops gracefully
        # below when the schedule lands on grid.  q_tiles is supported
        # by every schedule.)
        raise ValueError(
            f"fuse_denom is a resident-schedule option (kernel={kernel!r})")

    kv_bytes = 2 * Tk * D * (qdtype.itemsize
                             + (mxu_dtype.itemsize if needs_cast else 0))
    # fuse_denom's ones-extended V (and K-cast, when dtypes differ)
    # scratch counts against the same VMEM residency budget
    fd_scr_bytes = (
        Tk * (D + 1 + (D if qdtype != mxu_dtype else 0))
        * mxu_dtype.itemsize)
    auto_kernel = kernel == "auto"
    if auto_kernel:
        kernel = ("resident" if kv_bytes <= _RESIDENT_KV_BYTES
                  else "grid")
    if kernel not in ("resident", "grid", "grid_resident",
                      "resident_skew"):
        raise ValueError(f"unknown flash kernel {kernel!r}")
    if kernel == "resident_skew":
        # same rule as the fuse_denom check above: silently ignoring an
        # explicit schedule option would record fake sweep results
        if q_tiles > 1:
            raise ValueError("resident_skew is a single-chain schedule "
                             "(the skewed score carry IS its overlap "
                             "mechanism); q_tiles > 1 is not supported")
        if chunk_k is not None:
            raise ValueError("resident_skew folds whole K blocks (the "
                             "score carry spans block_k); chunk_k is "
                             "not supported")
        if kv_cast_scratch:
            raise ValueError("resident_skew casts K/V per block read; "
                             "kv_cast_scratch is not supported")
    if auto_fd:
        # the ones column rides free only when D and D+1 pad to the
        # same 128-lane tile (D=64 -> 65 both pad to 128; D=128 -> 129
        # pads to 256, doubling every PV matmul) — measured at D=64 as
        # the fastest schedule (0.19 vs 0.16 MXU frac, honest-timing
        # r04 sweep; confirmed in every window swept)
        fuse_denom = (kernel == "resident" and D % 128 != 0
                      and kv_bytes + fd_scr_bytes <= _RESIDENT_KV_BYTES)
    elif fuse_denom and auto_kernel:
        # distributed callers forward tuned opts without knowing each
        # shard's size (docs/parallelism.md) — under kernel="auto" the
        # resident-only hint drops when the schedule lands on grid (or
        # when its scratch would blow the residency budget); q_tiles
        # carries over to the grid schedule.  An EXPLICIT resident
        # kernel keeps the explicit option unconditionally.
        if kernel != "resident" \
                or kv_bytes + fd_scr_bytes > _RESIDENT_KV_BYTES:
            fuse_denom = False

    if auto_sched and chunk_k is None and _AUTO_CHUNK_K is not None:
        ck = _snap_chunk(_AUTO_CHUNK_K, bk)

    # snap q_tiles down until the sub-tiles are 8-row-aligned divisors
    # of the (possibly auto-shrunk) q block — the same keep-working
    # contract as the block halving and chunk snapping above
    while q_tiles > 1 and (bq % q_tiles != 0
                           or (bq // q_tiles) % 8 != 0):
        q_tiles -= 1

    if window is not None:
        # sliding-window attention: the streaming (grid) schedules own
        # the block liveness logic; the resident family's fori bounds
        # do not model a window
        if not causal:
            raise ValueError("window requires causal=True (a sliding "
                             "window is a trailing-context mask)")
        if window < 1:
            raise ValueError(f"window={window} must be >= 1")
        if kernel == "resident" and auto_kernel:
            kernel = "grid"   # auto landed on resident: move to grid
        if kernel not in ("grid", "grid_resident"):
            # same explicit-option contract as fuse_denom/resident_skew
            # above: silently running a different schedule than the one
            # named would record fake sweep results
            raise ValueError("window is a grid-schedule option "
                             f"(kernel={kernel!r})")
        fuse_denom = False    # resident-only option can't apply
    if static_max is not None:
        if kernel == "resident_skew":
            # the skew schedule's carried score block assumes the
            # dynamic fold; silently running it would record fake
            # sweep results (same contract as its other options)
            raise ValueError("static_max is not supported by the "
                             "resident_skew schedule")
        static_max = float(static_max)
    return (causal, bq, bk, ck, interpret, mxu_dtype, kernel,
            needs_cast, q_tiles, fuse_denom, window, static_max)


def _flash_call_packed(qp, kp, vp, causal, block_q, block_k, interpret,
                       mxu_dtype, kernel, chunk_k=None,
                       kv_cast_scratch=False, q_tiles=None,
                       fuse_denom=None, window=None, static_max=None):
    """Core entry on HEAD-PACKED operands [N, T, D] (N = batch x heads
    flattened — the splash-attention layout).  This is the zero-copy
    path: no transposes touch HBM; callers that keep activations packed
    (the model families do) pay only the kernel itself.
    GROUPED-QUERY ATTENTION (GQA): when K/V arrive with FEWER packed
    heads than q — shape [Nk, Tk, D] with N % Nk == 0 — each K/V head
    serves N/Nk consecutive q heads (q row n reads K/V row
    n // (N // Nk)).  Zero-copy on BOTH paths: the forward's K/V block
    index maps share rows across the group, and the backward reads the
    grouped K/V the same way (dq kernel, b//G maps) while the dkv
    kernel folds the whole group's dK/dV on an extended accumulation
    axis — no expansion touches HBM in either direction.

    Returns (out [N, T, D], lse [N, T] f32)."""
    N, T, D = qp.shape
    Tk = kp.shape[1]
    if (kp.shape != vp.shape or kp.shape[2] != D
            or kp.shape[0] == 0 or N % kp.shape[0] != 0):
        raise ValueError(f"k/v shape {kp.shape}/{vp.shape} incompatible "
                         f"with q {qp.shape} (K/V heads must divide "
                         f"q heads for GQA)")
    if causal and Tk != T:
        raise ValueError("causal masking requires Tq == Tk "
                         "(cross-length attention has no diagonal)")
    kv_group = N // kp.shape[0]
    # everything static is resolved; the traced part goes through the
    # custom-vjp boundary so jax.grad works on every entry point
    cfg = _resolve_schedule(T, Tk, D, qp.dtype, causal, block_q,
                            block_k, interpret, mxu_dtype, kernel,
                            chunk_k, kv_cast_scratch, q_tiles,
                            fuse_denom, window, static_max) + (kv_group,)
    return _flash_packed_diff(qp, kp, vp, cfg)


def _flash_forward_impl(qp, kp, vp, cfg):
    """The schedule dispatch — resolved static config only (see
    `_flash_call_packed`, which owns validation/auto-tuning)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    (causal, bq, bk, ck, interpret, mxu_dtype, kernel, needs_cast,
     q_tiles, fuse_denom, window, static_max, kv_group) = cfg
    g = kv_group  # q-heads per K/V head (1 = plain MHA)
    N, T, D = qp.shape
    Tk = kp.shape[1]
    nq, nk = T // bq, Tk // bk
    scale = _LOG2E / float(D) ** 0.5
    vma = _vma_of(qp, kp, vp)

    q_spec3 = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                           memory_space=pltpu.VMEM)
    out_shapes = (_sds((N, T, D), qp.dtype, vma),
                  _sds((N, T, 1), jnp.float32, vma))

    if kernel in ("resident", "resident_skew"):
        grid = (N, nq)
        q_spec = pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0),
                              memory_space=pltpu.VMEM)
        kv_spec = pl.BlockSpec((1, Tk, D), lambda b, i: (b // g, 0, 0),
                               memory_space=pltpu.VMEM)
        lse_spec = pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0),
                                memory_space=pltpu.VMEM)
        if kernel == "resident_skew":
            # single-chain, per-block-read casts: no scratch variants
            scratch = []
            kfn = functools.partial(
                _flash_kernel_resident_skew, scale=scale, causal=causal,
                block_q=bq, block_k=bk, T=Tk, mxu_dtype=mxu_dtype)
        else:
            # one-time K/V cast scratch (see kernel docstring) — only
            # when the input is not already in MXU format.  fuse_denom
            # builds the ones-extended V in scratch regardless of dtype.
            if fuse_denom:
                scratch = ([pltpu.VMEM((Tk, D), mxu_dtype)]
                           if qp.dtype != mxu_dtype else [])
                scratch += [pltpu.VMEM((Tk, D + 1), mxu_dtype)]
            elif needs_cast:
                scratch = [pltpu.VMEM((Tk, D), mxu_dtype),
                           pltpu.VMEM((Tk, D), mxu_dtype)]
            else:
                scratch = []
            kfn = functools.partial(
                _flash_kernel_resident, scale=scale, causal=causal,
                block_q=bq, block_k=bk, chunk_k=ck, T=Tk,
                mxu_dtype=mxu_dtype, q_tiles=q_tiles,
                fuse_denom=fuse_denom, static_max=static_max)
        out, lse = pl.pallas_call(
            kfn, out_shape=out_shapes, grid=grid,
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=(q_spec, lse_spec),
            scratch_shapes=scratch,
            # with cast/fused scratch the q-blocks of one batch-head must
            # run in-order ("arbitrary") so the iq==0 build is visible to
            # the rest; without it every cell is independent ("parallel")
            compiler_params=_tpu_compiler_params(
                dimension_semantics=(
                    ("parallel", "arbitrary")
                    if (needs_cast or fuse_denom)
                    else ("parallel", "parallel"))),
            interpret=interpret,
        )(qp, kp, vp)
    else:
        # with a sliding window the k grid dimension is BOUNDED to the
        # O(window/bk) blocks a q block can actually see; the K/V index
        # maps fetch from the same shifted base (clamped at the last
        # block — a clamped fetch belongs to a dead cell), so
        # out-of-window K/V blocks are never DMA'd
        if window is not None:
            nk_eff = min(nk, (window - 1 + bq + bk - 1) // bk + 1)

            def _kv_block(i, j):
                first = _window_first_block(i, bq, bk, window)
                return jnp.minimum(first + j, nk - 1)
        else:
            nk_eff = nk

            def _kv_block(i, j):
                return j
        grid = (N, nq, nk_eff)
        kv_resident = kernel == "grid_resident"
        if kv_resident:
            # whole-row K/V block with a PINNED index map: Pallas only
            # re-DMAs a block whose index changes, so the row is fetched
            # once per batch-head while the cells keep the grid
            # schedule's static predication and scratch carries
            kv_spec = pl.BlockSpec((1, Tk, D),
                                   lambda b, i, j: (b // g, 0, 0),
                                   memory_space=pltpu.VMEM)
        else:
            kv_spec = pl.BlockSpec(
                (1, bk, D),
                lambda b, i, j: (b // g, _kv_block(i, j), 0),
                memory_space=pltpu.VMEM)
        lse_spec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                                memory_space=pltpu.VMEM)
        kfn = functools.partial(
            _flash_kernel_grid, scale=scale, causal=causal, block_q=bq,
            block_k=bk, chunk_k=ck, nk=nk_eff, nk_total=nk,
            mxu_dtype=mxu_dtype,
            kv_resident=kv_resident, q_tiles=q_tiles, window=window,
            static_max=static_max)
        out, lse = pl.pallas_call(
            kfn, out_shape=out_shapes, grid=grid,
            in_specs=[q_spec3, kv_spec, kv_spec],
            out_specs=(q_spec3, lse_spec),
            scratch_shapes=[
                pltpu.VMEM((bq, D), jnp.float32),   # acc
                pltpu.VMEM((bq, 1), jnp.float32),   # running max
                pltpu.VMEM((bq, 1), jnp.float32),   # running denom
            ],
            # the k dimension carries the accumulator (sequential); the
            # bh/q-block dims are independent
            compiler_params=_tpu_compiler_params(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=interpret,
        )(qp, kp, vp)

    return out, lse.reshape(N, T)


# ---------------------------------------------------------------------------
# backward pass (jax.custom_vjp)
# ---------------------------------------------------------------------------
#
# The standard flash-attention backward, TPU-shaped: with the saved
# (out, lse), normalized probabilities rebuild per block as
# P = exp2(s2 - lse2) (log2 domain like the forward), and
#
#   dV_j  = sum_i P_ij dO_i
#   dS_ij = P_ij * (dO_i . V_j - dvec_i),  dvec_i = dO_i . out_i - dlse_i
#   dQ_i  = a * sum_j dS_ij K_j,   dK_j = a * sum_i dS_ij Q_i
#
# (the dlse term folds the lse output's cotangent in — ring attention
# differentiates through its lse-weighted shard merge).  Two grid
# kernels: dQ accumulates over k blocks per q block; dK/dV accumulate
# over q blocks per k block.  Causal cells are predicated off exactly
# like the forward grid schedule.

def _flash_bwd_p_block(q2, kb, l2, row0, col0, masked, window=None):
    """Rebuild the normalized probability block [rows(q2), rows(kb)]
    from prescaled q2 (a*log2e folded in) and the log2-domain lse; dead
    rows (lse = NEG_INF, fully-masked forward) produce zeros.  `masked`
    applies the causal row >= col test (AND the sliding-window
    row - col < window test when set) against the (row0, col0) global
    offsets — callers predicate it to the straddling cells only (past
    cells need no mask; same lane-work split as the forward grid
    kernel)."""
    rq, rk = q2.shape[0], kb.shape[0]
    s2 = jax.lax.dot_general(q2, kb, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    p = jnp.where(l2 <= NEG_INF / 2, 0.0, jnp.exp2(s2 - l2))
    if masked:
        rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (rq, rk), 0)
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (rq, rk), 1)
        keep = rows >= cols
        if window is not None:
            keep = keep & (rows - cols < window)
        p = jnp.where(keep, p, 0.0)
    return p


def _window_first_block(iq, block_q, block_k, window):
    """Index of the first k block any row of q-block `iq` can see under
    the sliding window — the k-grid base the bounded schedule and its
    K/V index maps share."""
    lo = iq * block_q - (window - 1)     # earliest visible column
    return jnp.maximum(lo, 0) // block_k


def _grid_live_masked(iq, ik, bq, bk, causal, window=None):
    """(live, masked) cell predicates shared by the forward grid kernel
    and BOTH backward kernels (one copy, so forward and backward can
    never disagree): skip future cells (and, under a sliding window,
    cells strictly before every row's window) entirely; mask only the
    cells straddling the diagonal or the window edge."""
    if not causal:
        return True, False
    live = ik * bk <= iq * bq + bq - 1
    diag = (ik * bk + bk - 1 > iq * bq) & live
    if window is not None:
        live = live & (ik * bk + bk - 1 > iq * bq - window)
        wedge = ik * bk < iq * bq + bq - window
        diag = (diag | wedge) & live
    return live, diag


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, l2_ref, dvec_ref,
                         dq_ref, acc, *, causal, bq, bk, nk, nk_total,
                         mxu_dtype, inv_scale_a, chunk_k, window=None):
    """dQ cell: accumulate ds @ K over the k blocks of one q block.
    Each cell runs as an UNROLLED run of chunk_k sub-chunks — the same
    MXU/VPU pipelining lever as the forward fold: chunk c's exp2/ds VPU
    work has no dependence on chunk c+1's matmuls, and the per-chunk
    partial dq contributions are additive."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    j = pl.program_id(2)
    # under a sliding window the k dimension is bounded exactly like
    # the forward grid: j counts the O(window) visible blocks from the
    # shifted base, and ik is the REAL k-block index
    ik = j + (_window_first_block(iq, bq, bk, window)
              if window is not None else 0)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    live, diag = _grid_live_masked(iq, ik, bq, bk, causal, window)
    if window is not None:
        # phantom cells past the REAL k range (the bounded span's tail
        # with a clamped fetch) must stay dead regardless of the
        # causal/window algebra
        live = live & (ik < nk_total)
        diag = diag & live

    def body(masked):
        q2 = q_ref[0].astype(mxu_dtype)      # pre-scaled on the host
        do = do_ref[0].astype(mxu_dtype)
        l2 = l2_ref[0]
        dvec = dvec_ref[0]
        total = acc[:]
        for c in range(bk // chunk_k):
            kb = k_ref[0, pl.ds(c * chunk_k, chunk_k), :].astype(mxu_dtype)
            vb = v_ref[0, pl.ds(c * chunk_k, chunk_k), :].astype(mxu_dtype)
            p = _flash_bwd_p_block(q2, kb, l2, iq * bq,
                                   ik * bk + c * chunk_k, masked,
                                   window)
            dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - dvec)
            total = total + jax.lax.dot_general(
                ds.astype(mxu_dtype), kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        acc[:] = total

    if causal:
        @pl.when(diag)
        def _diag_body():
            body(masked=True)

        @pl.when(live & jnp.logical_not(diag))
        def _past_body():
            body(masked=False)
    else:
        body(masked=False)

    @pl.when(j == nk - 1)
    def _fin():
        dq_ref[0] = (acc[:] * inv_scale_a).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, l2_ref, dvec_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, causal, bq,
                          bk, nq, nq_total, mxu_dtype, chunk_q,
                          window=None, group=1):
    """dK/dV cell: accumulate over the q blocks of one k block.  The
    q block is processed as an UNROLLED run of chunk_q sub-chunks (the
    roles of q and k swap relative to the dq kernel, so here the chunk
    axis is q) — independent sub-chunks whose partial dK/dV
    contributions are additive, giving Mosaic MXU/VPU overlap.

    GQA (``group`` > 1): the accumulation axis spans group * nq steps —
    every q head of this K/V head's group folds its contribution into
    the SAME dk/dv accumulators (the in-kernel transpose of the
    forward's zero-copy row sharing), so K/V never expand and no
    group-sum pass runs outside the kernel.  The q-side index maps pick
    (q head, q block) = divmod(j, nq); the mask algebra only needs the
    q-BLOCK index since every q head shares the same positions."""
    from jax.experimental import pallas as pl

    ik = pl.program_id(1)
    j = pl.program_id(2)
    j2 = j % nq if group > 1 else j
    # bounded q iteration under a window: the q blocks that can see
    # k-block ik start at the causal lower bound (ik*bk)//bq and end
    # O(window) blocks later; j2 counts from that base
    iq = j2 + ((ik * bk) // bq if window is not None else 0)

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live, diag = _grid_live_masked(iq, ik, bq, bk, causal, window)
    if window is not None:
        # CRITICAL: phantom cells past the REAL q range are causally
        # LIVE (future q rows attend past k columns), and their clamped
        # q fetches would accumulate garbage under wrong mask offsets —
        # bound liveness by the real grid
        live = live & (iq < nq_total)
        diag = diag & live

    def body(masked):
        kb = k_ref[0].astype(mxu_dtype)
        vb = v_ref[0].astype(mxu_dtype)
        dk_tot, dv_tot = dk_acc[:], dv_acc[:]
        for c in range(bq // chunk_q):
            sl = pl.ds(c * chunk_q, chunk_q)
            q2 = q_ref[0, sl, :].astype(mxu_dtype)
            do = do_ref[0, sl, :].astype(mxu_dtype)
            p = _flash_bwd_p_block(q2, kb, l2_ref[0, sl, :],
                                   iq * bq + c * chunk_q, ik * bk,
                                   masked, window)
            pc = p.astype(mxu_dtype)
            dv_tot = dv_tot + jax.lax.dot_general(
                pc, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - dvec_ref[0, sl, :])).astype(mxu_dtype)
            dk_tot = dk_tot + jax.lax.dot_general(
                ds, q2, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        dk_acc[:] = dk_tot
        dv_acc[:] = dv_tot

    if causal:
        @pl.when(diag)
        def _diag_body():
            body(masked=True)

        @pl.when(live & jnp.logical_not(diag))
        def _past_body():
            body(masked=False)
    else:
        body(masked=False)

    @pl.when(j == nq * group - 1)
    def _fin():
        # q2 carries the a*log2e prescale, so dK needs it divided back
        # out on top of its own `a` factor: a / (a*log2e) = 1/log2e
        dk_ref[0] = (dk_acc[:] * (1.0 / _LOG2E)).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(qp, kp, vp, out, lse, g_out, g_lse, cfg):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    (causal, bq, bk, ck, interpret, mxu_dtype, _kernel, _nc, _qt,
     _fd, window, _sm, kvg) = cfg
    N, T, D = qp.shape
    Tk = kp.shape[1]
    G = kvg if kvg else 1          # q heads per K/V head (GQA group)
    Nk = N // G                    # kp/vp rows: [Nk, Tk, D], grouped
    nq, nk = T // bq, Tk // bk
    a = 1.0 / float(D) ** 0.5
    # sub-chunk widths for the unrolled backward cells (the forward's
    # MXU/VPU pipelining lever): ck arrives resolved from the forward
    # call and already divides bk — dq chunks over k at ck directly;
    # dkv chunks over q, re-snapped against bq
    ckb = ck
    ckq = _snap_chunk(ck, bq)
    vma = _vma_of(qp, kp, vp, g_out)

    # host-side prep: prescaled q (exp2 domain), log2-domain lse, and
    # the dS offset with the lse cotangent folded in
    q2 = (qp.astype(jnp.float32) * (a * _LOG2E)).astype(qp.dtype)
    l2 = (lse * _LOG2E)[..., None]                       # [N, T, 1]
    dvec = jnp.sum(g_out.astype(jnp.float32)
                   * out.astype(jnp.float32), axis=-1, keepdims=True)
    if g_lse is not None:
        dvec = dvec - g_lse.astype(jnp.float32)[..., None]

    # under a sliding window both backward grids are BOUNDED like the
    # forward: the k dimension of dq spans only the O(window) visible
    # blocks from each q block's shifted base, and the q dimension of
    # dkv spans only the O(window) q blocks that can see each k block
    # (clamped fetches belong to dead, predicated-off cells)
    if window is not None:
        nk_eff = min(nk, (window - 1 + bq + bk - 1) // bk + 1)
        nq_eff = min(nq, (bk + window - 2) // bq + 2)

        def _kblk(i, j):
            return jnp.minimum(
                _window_first_block(i, bq, bk, window) + j, nk - 1)

        def _qblk(jk, j2):
            return jnp.minimum((jk * bk) // bq + j2, nq - 1)
    else:
        nk_eff, nq_eff = nk, nq

        def _kblk(i, j):
            return j

        def _qblk(jk, j2):
            return j2

    qb_spec = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                           memory_space=pltpu.VMEM)
    # GQA: q row b reads K/V row b // G — the same zero-copy row
    # sharing as the forward's index maps; no expanded K/V exists
    kb_spec = pl.BlockSpec((1, bk, D),
                           lambda b, i, j: (b // G, _kblk(i, j), 0),
                           memory_space=pltpu.VMEM)
    ql_spec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                           memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal, bq=bq,
                          bk=bk, nk=nk_eff, nk_total=nk,
                          mxu_dtype=mxu_dtype,
                          inv_scale_a=a, chunk_k=ckb, window=window),
        out_shape=_sds((N, T, D), qp.dtype, vma),
        grid=(N, nq, nk_eff),
        in_specs=[qb_spec, kb_spec, kb_spec, qb_spec, ql_spec, ql_spec],
        out_specs=qb_spec,
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q2, kp, vp, g_out, l2, dvec)

    # dK/dV: swap the roles — k blocks on the parallel axis, q blocks
    # accumulated sequentially.  Under GQA the sequential axis spans
    # the WHOLE q-head group (G * nq_eff steps): q row = b*G + i//nq,
    # q block = i%nq — each K/V head's dk/dv fold their group's
    # contributions in-kernel, expansion-free (ADVICE r4: the old path
    # repeated K/V G x and group-summed outside, scaling backward HBM
    # with the full q-head count)
    def _qrow(b, i):
        return b * G + i // nq_eff if G > 1 else b

    def _qblk2(jk, i):
        return _qblk(jk, i % nq_eff) if G > 1 else _qblk(jk, i)

    qs_spec = pl.BlockSpec((1, bq, D),
                           lambda b, jk, i: (_qrow(b, i), _qblk2(jk, i),
                                             0),
                           memory_space=pltpu.VMEM)
    ks_spec = pl.BlockSpec((1, bk, D), lambda b, jk, i: (b, jk, 0),
                           memory_space=pltpu.VMEM)
    ls_spec = pl.BlockSpec((1, bq, 1),
                           lambda b, jk, i: (_qrow(b, i), _qblk2(jk, i),
                                             0),
                           memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal, bq=bq,
                          bk=bk, nq=nq_eff, nq_total=nq,
                          mxu_dtype=mxu_dtype,
                          chunk_q=ckq, window=window, group=G),
        out_shape=(_sds((Nk, Tk, D), kp.dtype, vma),
                   _sds((Nk, Tk, D), vp.dtype, vma)),
        grid=(Nk, nk, nq_eff * G),
        in_specs=[qs_spec, ks_spec, ks_spec, qs_spec, ls_spec, ls_spec],
        out_specs=(ks_spec, ks_spec),
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q2, kp, vp, g_out, l2, dvec)

    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_packed_diff(qp, kp, vp, cfg):
    return _flash_forward_impl(qp, kp, vp, cfg)


def _flash_diff_fwd(qp, kp, vp, cfg):
    # symbolic_zeros=True wraps primals in (value, perturbed) records
    qp, kp, vp = (getattr(x, "value", x) for x in (qp, kp, vp))
    out, lse = _flash_forward_impl(qp, kp, vp, cfg)
    return (out, lse), (qp, kp, vp, out, lse)


def _flash_diff_bwd(cfg, res, cts):
    from jax.custom_derivatives import SymbolicZero

    qp, kp, vp, out, lse = res
    g_out, g_lse = cts
    # callers that discard lse (most) get a SYMBOLIC zero cotangent —
    # skip the dvec subtract instead of materializing a zero [N, T]
    if isinstance(g_lse, SymbolicZero):
        g_lse = None
    if isinstance(g_out, SymbolicZero):  # lse-only losses (rare)
        g_out = jnp.zeros(out.shape, out.dtype)
    # GQA and plain share ONE path: _flash_backward reads grouped K/V
    # through b//G index maps (dq) and folds each group's dK/dV inside
    # the dkv kernel's extended accumulation axis — K/V are never
    # expanded and no group-sum pass runs outside (ADVICE r4; the
    # forward's zero-copy row sharing, transposed)
    return _flash_backward(qp, kp, vp, out, lse, g_out, g_lse, cfg)


_flash_packed_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd,
                          symbolic_zeros=True)


def _flash_call(q, k, v, causal, block_q, block_k, interpret, mxu_dtype,
                kernel, q_tiles=None, fuse_denom=None, window=None,
                static_max=None):
    """BTHD-layout wrapper: packs [B,T,H,D] -> [B*H,T,D] around the
    core call (one HBM transpose per operand direction; XLA hoists the
    K/V packs out of iteration loops — callers on the hot path should
    still prefer the packed entry points).  A lane-blocked in-place
    alternative (index maps picking each head's 128-aligned lane chunk
    of a [B,T,H*D] view) was measured SLOWER than these transposes on
    the r04 chip — the per-head 512-byte strided DMA costs more than
    the packs — so the wrapper deliberately stays on the packing path.

    GQA: k/v may carry FEWER heads than q ([B, Tk, G, D], H % G == 0) —
    each K/V head serves H/G consecutive q heads, expansion-free in
    the forward (see :func:`_flash_call_packed`).

    Returns (out [B,T,H,D], lse [B,H,T] f32)."""
    B, T, H, D = q.shape
    G = k.shape[2] if k.ndim == 4 else -1
    if (k.shape != v.shape or k.ndim != 4 or k.shape[0] != B
            or k.shape[3] != D or G <= 0 or H % G != 0):
        raise ValueError(f"k/v shape {k.shape}/{v.shape} incompatible "
                         f"with q {q.shape} (K/V heads must divide "
                         f"q heads for GQA)")

    def pack(x):  # [B, t, h, D] -> [B*h, t, D] (h = that tensor's heads)
        t, h = x.shape[1], x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(B * h, t, D)

    out, lse = _flash_call_packed(pack(q), pack(k), pack(v), causal,
                                  block_q, block_k, interpret, mxu_dtype,
                                  kernel, q_tiles=q_tiles,
                                  fuse_denom=fuse_denom, window=window,
                                  static_max=static_max)
    return (out.reshape(B, H, T, D).transpose(0, 2, 1, 3),
            lse.reshape(B, H, T))


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret", "mxu_dtype", "kernel",
                                    "q_tiles", "fuse_denom", "window",
                                    "static_max"))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 256,
                    block_k: int = 512, interpret: bool = False,
                    mxu_dtype=jnp.bfloat16, kernel: str = "auto",
                    q_tiles: int | None = None,
                    fuse_denom: bool | None = None,
                    window: int | None = None,
                    static_max: float | None = None):
    """q, k, v: [B, T, H, D] -> [B, T, H, D] (self-attention, optional
    causal mask).  T must be divisible by the (auto-shrunk) block sizes.

    `mxu_dtype` is the matmul input format (bf16 default — the MXU's
    native rate; accumulation is always f32).  Pass jnp.float32 for
    reference-exact numerics at ~1/4 the throughput.

    `kernel` selects the schedule: "resident" pins the whole K/V row in
    VMEM per batch-head (fetched once; best while it fits), "grid"
    streams K/V blocks per q-block (any T), "auto" picks by K/V size.
    `q_tiles` (any schedule) and `fuse_denom` (resident only) are the
    throughput options (see :func:`flash_attention_packed`); leaving
    both at None applies the tuned auto schedule (plain single fold
    chain; fused denominator where its ones column is lane-tile-free,
    e.g. D=64)."""
    out, _lse = _flash_call(q, k, v, causal, block_q, block_k, interpret,
                            mxu_dtype, kernel, q_tiles, fuse_denom,
                            window, static_max)
    return out


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret", "mxu_dtype", "kernel",
                                    "q_tiles", "fuse_denom", "window",
                                    "static_max"))
def flash_attention_lse(q, k, v, causal: bool = False, block_q: int = 256,
                        block_k: int = 512, interpret: bool = False,
                        mxu_dtype=jnp.bfloat16, kernel: str = "auto",
                        q_tiles: int | None = None,
                        fuse_denom: bool | None = None,
                        window: int | None = None,
                        static_max: float | None = None):
    """Like :func:`flash_attention` but also returns the log-sum-exp
    statistics: (out [B, T, H, D], lse [B, H, T] fp32).  Partial results
    over different K/V shards combine exactly via lse weighting — the
    cross-shard fold ring attention applies around the ICI ring."""
    return _flash_call(q, k, v, causal, block_q, block_k, interpret,
                       mxu_dtype, kernel, q_tiles, fuse_denom, window,
                       static_max)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret", "mxu_dtype", "kernel",
                                    "chunk_k", "kv_cast_scratch",
                                    "q_tiles", "fuse_denom", "window",
                                    "static_max"))
def flash_attention_packed(q, k, v, causal: bool = False,
                           block_q: int = 256, block_k: int = 512,
                           interpret: bool = False,
                           mxu_dtype=jnp.bfloat16, kernel: str = "auto",
                           chunk_k: int | None = None,
                           kv_cast_scratch: bool = False,
                           q_tiles: int | None = None,
                           fuse_denom: bool | None = None,
                           window: int | None = None,
                           static_max: float | None = None):
    """Zero-copy entry on HEAD-PACKED operands: q, k, v are [N, T, D]
    with N = batch x heads flattened (the splash-attention layout).
    Unlike the [B, T, H, D] wrapper this moves NO bytes outside the
    kernel — callers that keep activations packed (the transformer
    family does between its projections) get the kernel at full rate.
    Returns out [N, T, D].

    `q_tiles` (every schedule) splits each q block into that many
    independent sub-tiles whose folds interleave — MXU/VPU overlap
    across dependence chains; it snaps down to a valid 8-row-aligned
    split.  `fuse_denom` (resident only; dropped when "auto" lands on
    grid) rides the softmax row-sum on the PV matmul via a
    ones-extended V — one fewer VPU pass per fold, free where D pads
    to the same lane tile (D=64).  Leaving either at None applies the
    tuned AUTO schedule from the measured table at the top of this
    module: the plain single fold chain over whole K blocks, with the
    fused denominator exactly where its ones column is lane-tile-free;
    explicit values (incl. q_tiles=1 / fuse_denom=False) always win.

    `static_max` (OPT-IN; resident and grid schedules) pins the
    softmax shift to a
    constant instead of the running row max: the max reduction, shift
    clamp, alpha rescale and masked-p re-zero leave the VPU budget —
    the fold's measured bottleneck at D=128.  EXACT (same p/l ratios,
    same lse) whenever every scaled logit s = q.k * log2e/sqrt(D)
    stays within f32 exponent range of the pin: overflow at
    s > static_max + 127, underflow only for weights ~2^-149 below
    the pin (numerically irrelevant).  A pin of 40 covers |logits|
    up to ~27 nats — far beyond trained-model attention logits; it is
    NOT safe for adversarially scaled inputs, which is why the
    dynamic-max fold stays the default.  See the kernel docstrings."""
    out, _lse = _flash_call_packed(q, k, v, causal, block_q, block_k,
                                   interpret, mxu_dtype, kernel, chunk_k,
                                   kv_cast_scratch, q_tiles, fuse_denom,
                                   window, static_max)
    return out


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret", "mxu_dtype", "kernel",
                                    "chunk_k", "kv_cast_scratch",
                                    "q_tiles", "fuse_denom", "window",
                                    "static_max"))
def flash_attention_packed_lse(q, k, v, causal: bool = False,
                               block_q: int = 256, block_k: int = 512,
                               interpret: bool = False,
                               mxu_dtype=jnp.bfloat16, kernel: str = "auto",
                               chunk_k: int | None = None,
                               kv_cast_scratch: bool = False,
                               q_tiles: int | None = None,
                               fuse_denom: bool | None = None,
                               window: int | None = None,
                               static_max: float | None = None):
    """Head-packed [N, T, D] variant returning (out [N, T, D],
    lse [N, T] fp32) — the distributed callers' entry (ring attention
    folds shard partials via the lse)."""
    return _flash_call_packed(q, k, v, causal, block_q, block_k,
                              interpret, mxu_dtype, kernel, chunk_k,
                              kv_cast_scratch, q_tiles, fuse_denom,
                              window, static_max)
