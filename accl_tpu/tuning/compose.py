"""Two-level hierarchical collectives composed from driver primitives.

:class:`HierarchicalComm` assembles topology-aware collectives from the
EXISTING per-call driver surface — reduce_scatter-within-group →
allreduce-across-groups → allgather-within-group for allreduce, and the
reduce_scatter / allgather / bcast / scatter / gather analogues — over
per-axis sub-communicators minted from a :class:`~accl_tpu.tuning.
topology.Fabric`.  Every stage is an ordinary ``ACCL`` call, so a
composition is capturable with ``ACCL.capture_plan`` (the decomposition
overhead is then paid once per r12 plan, replays ride the plan ring)
and observable through the normal flight/metrics/trace machinery.

Layout contract: the stage pairing is chosen so results are element-
for-element identical to the flat collective.  For SUM reductions on
floating dtypes the two-level reduction ORDER differs from the flat
engine's, so float results are bitwise-equal only when the additions
are exact (integer-valued data, or integer/MAX lanes — the lossless
cases tests/test_tuning.py pins bitwise on both backends).

Sub-communicator discipline: the group family is iterated in the same
deterministic global order on every rank; a rank reserves (burns) the
comm ids of groups it is not a member of via ``ACCL.reserve_
communicator``, so the id spaces stay aligned world-wide — the
``create_communicator`` ordering contract applied to disjoint group
families.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..constants import ACCLError, ReduceFunction
from .topology import Fabric


class HierarchicalComm:
    """Two-level collectives for one driver over one fabric.

    Construction mints the per-axis sub-communicators (inner = groups
    along the last, rank-contiguous axis; outer = the complementary
    partition) and is therefore collective in the create-order sense:
    every rank of the world must construct its ``HierarchicalComm``
    with the SAME fabric before any composed call, exactly like
    ``create_communicator``.  Scratch buffers are allocated lazily and
    cached, so a captured composition replays against stable
    addresses.

    Role assignment: the layout-sensitive collectives (reduce_scatter,
    allgather, scatter, gather) always stage their within-group phase
    on the inner (contiguous) axis — that is what makes the composed
    result element-identical to the flat call.  allreduce and bcast
    are layout-free, so their within role follows the fabric's
    measured axis health (``Fabric.within_axis``): a demoted (slow)
    inner axis swaps the heavy reduce_scatter+allgather traffic onto
    the healthier outer partition.
    """

    def __init__(self, accl, fabric: Optional[Fabric] = None):
        self.accl = accl
        # default fabric: probe device coords only on the TPU backend
        # — an emu composer must never import jax / touch the chip
        # claim just to factorize its world
        self.fabric = fabric or Fabric.for_world(
            accl.size,
            probe=getattr(accl.device, "comm_table_is_shared", False))
        if self.fabric.nranks != accl.size:
            raise ACCLError(
                f"HierarchicalComm: fabric covers {self.fabric.nranks} "
                f"ranks but the world has {accl.size}")
        self.flat = self.fabric.trivial
        self._scratch: dict = {}
        if self.flat:
            return
        # the inner axis is the LAST non-trivial one: its rank stride is
        # the product of the (extent-1) axes behind it, i.e. 1 — inner
        # groups are rank-contiguous, which is what makes the staged
        # slab layouts element-identical to the flat collectives
        self._inner_axis = max(
            i for i, a in enumerate(self.fabric.shape) if a > 1)
        #: True when measured demotion moved the heavy within role off
        #: the inner axis (allreduce/bcast swap stage comms)
        self.swapped = self.fabric.within_axis() != self._inner_axis
        rank = accl.rank
        inner_group: Optional[list] = None
        outer_group: Optional[list] = None
        inner_comm = outer_comm = -1
        # deterministic global order: inner groups first, then outer —
        # every rank iterates the same list and burns the ids of the
        # groups it is not in, so group G gets ONE world-wide comm id
        inner_groups = self.fabric.groups(self._inner_axis)
        outer_groups = self.fabric.groups_complement(self._inner_axis)
        for group in inner_groups:
            if rank in group:
                inner_group = group
                inner_comm = accl.create_communicator(group)
            else:
                accl.reserve_communicator()
        for group in outer_groups:
            if rank in group:
                outer_group = group
                outer_comm = accl.create_communicator(group)
            else:
                accl.reserve_communicator()
        if inner_group is None or outer_group is None:
            raise ACCLError(
                f"HierarchicalComm: rank {rank} is in no fabric group "
                f"(fabric {self.fabric.spec()})")
        self._inner_group: list = inner_group
        self._outer_group: list = outer_group
        self._inner_comm: int = inner_comm
        self._outer_comm: int = outer_comm

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------
    def _within(self) -> tuple:
        """(comm_id, group) of the heavy within stage for the
        layout-free collectives — honors measured demotion."""
        if self.swapped:
            return self._outer_comm, self._outer_group
        return self._inner_comm, self._inner_group

    def _across(self) -> tuple:
        if self.swapped:
            return self._inner_comm, self._inner_group
        return self._outer_comm, self._outer_group

    def _buf(self, tag: str, count: int, dtype):
        """Cached zero-initialized scratch: stable addresses across
        calls, so captured compositions replay against the same
        descriptor stream."""
        key = (tag, count, np.dtype(dtype).str)
        buf = self._scratch.get(key)
        if buf is None:
            buf = self.accl.create_buffer(count, np.dtype(dtype))
            buf.host[:] = 0
            buf.sync_to_device()
            self._scratch[key] = buf
        return buf

    def close(self) -> None:
        """Free the cached scratch (sub-communicators live with the
        driver, like every create_communicator result)."""
        for buf in self._scratch.values():
            free = getattr(buf, "free", None)
            if free is not None:
                free()
        self._scratch.clear()

    # ------------------------------------------------------------------
    # layout-free collectives: within role follows measured axis health
    # ------------------------------------------------------------------
    def allreduce(self, sendbuf, recvbuf, count: int,
                  function: ReduceFunction = ReduceFunction.SUM) -> None:
        """reduce_scatter(within) -> allreduce(across) -> allgather
        (within).  Non-divisible counts stage through padded scratch;
        the pad occupies the last chunk's tail beyond ``count`` and is
        DISCARDED by the truncating copy-out, so its content (zero at
        first use, possibly a prior larger call's stale elements on
        reuse) never reaches the result."""
        if self.flat:
            self.accl.allreduce(sendbuf, recvbuf, count, function)
            return
        w_comm, w_group = self._within()
        a_comm, _ = self._across()
        B = len(w_group)
        dtype = sendbuf.dtype
        chunk = -(-count // B)
        padded = chunk * B
        if padded == count:
            rs_in, ag_out = sendbuf, recvbuf
        else:
            rs_in = self._buf("ar_in", padded, dtype)
            ag_out = self._buf("ar_out", padded, dtype)
            # pad-tail invariant: everything past [0, count) is
            # DISCARDED — the final copy truncates to count — so the
            # tail's content (zero on first use; a smaller later count
            # may see a prior call's stale elements there) never
            # reaches a result.  Nothing may ever read ag_out's tail.
            self.accl.copy(sendbuf, rs_in, count)
        mid = self._buf("ar_mid", chunk, dtype)
        mid2 = self._buf("ar_mid2", chunk, dtype)
        self.accl.reduce_scatter(rs_in, mid, chunk, function,
                                 comm_id=w_comm)
        self.accl.allreduce(mid, mid2, chunk, function, comm_id=a_comm)
        self.accl.allgather(mid2, ag_out, chunk, comm_id=w_comm)
        if padded != count:
            self.accl.copy(ag_out, recvbuf, count)

    def bcast(self, buf, count: int, root: int) -> None:
        """bcast along the root's across-group, then within every
        within-group from the member aligned with the root."""
        if self.flat:
            self.accl.bcast(buf, count, root)
            return
        w_comm, w_group = self._within()
        a_comm, a_group = self._across()
        # stage 1: the across-group CONTAINING the root fans the data
        # to one delegate per within-group; ranks of the other across-
        # groups skip it (their across comm holds no data yet)
        if root in a_group:
            self.accl.bcast(buf, count, a_group.index(root),
                            comm_id=a_comm)
        # stage 2: within-group bcast from the delegate — the member
        # sharing the root's across-group (its within-group slot)
        delegate = next(m for m in w_group
                        if self._same_across_slot(m, root))
        self.accl.bcast(buf, count, w_group.index(delegate),
                        comm_id=w_comm)

    def _same_across_slot(self, a: int, b: int) -> bool:
        """True when ranks a and b share an across-group (occupy the
        same slot of their respective within-groups)."""
        if not self.swapped:
            # across groups share the inner coordinate
            return (self.fabric.coords[a][self._inner_axis]
                    == self.fabric.coords[b][self._inner_axis])
        # swapped: across groups are the inner (contiguous) lines —
        # shared slot means equal coords on every non-inner axis
        ca = tuple(c for i, c in enumerate(self.fabric.coords[a])
                   if i != self._inner_axis)
        cb = tuple(c for i, c in enumerate(self.fabric.coords[b])
                   if i != self._inner_axis)
        return ca == cb

    # ------------------------------------------------------------------
    # layout-sensitive collectives: within stage pinned to the inner
    # (rank-contiguous) axis so the result is element-identical to flat
    # ------------------------------------------------------------------
    def reduce_scatter(self, sendbuf, recvbuf, count: int,
                       function: ReduceFunction = ReduceFunction.SUM,
                       ) -> None:
        """RS across the outer partition (slab = count x inner-extent),
        then RS within the inner group — each rank ends owning exactly
        its flat-semantics chunk, no padding needed (the global input
        is count x P by construction)."""
        if self.flat:
            self.accl.reduce_scatter(sendbuf, recvbuf, count, function)
            return
        B = len(self._inner_group)
        slab = count * B
        mid = self._buf("rs_mid", slab, sendbuf.dtype)
        self.accl.reduce_scatter(sendbuf, mid, slab, function,
                                 comm_id=self._outer_comm)
        self.accl.reduce_scatter(mid, recvbuf, count, function,
                                 comm_id=self._inner_comm)

    def allgather(self, sendbuf, recvbuf, count: int) -> None:
        """AG within the inner group (count -> count x B), then AG
        across the outer partition (-> count x P, flat layout)."""
        if self.flat:
            self.accl.allgather(sendbuf, recvbuf, count)
            return
        B = len(self._inner_group)
        mid = self._buf("ag_mid", count * B, sendbuf.dtype)
        self.accl.allgather(sendbuf, mid, count, comm_id=self._inner_comm)
        self.accl.allgather(mid, recvbuf, count * B,
                            comm_id=self._outer_comm)

    def scatter(self, sendbuf, recvbuf, count: int, root: int) -> None:
        """scatter slabs along the root's outer group, then scatter
        within each inner group from the delegate."""
        if self.flat:
            self.accl.scatter(sendbuf, recvbuf, count, root)
            return
        B = len(self._inner_group)
        me = self.accl.rank
        slab = count * B
        if root in self._outer_group:  # me is one of root's delegates
            mid = self._buf("sc_mid", slab, recvbuf.dtype)
            self.accl.scatter(sendbuf if me == root else None, mid, slab,
                              self._outer_group.index(root),
                              comm_id=self._outer_comm)
        else:
            mid = None
        delegate = next(m for m in self._inner_group
                        if self._same_inner_slot(m, root))
        self.accl.scatter(mid, recvbuf, count,
                          self._inner_group.index(delegate),
                          comm_id=self._inner_comm)

    def gather(self, sendbuf, recvbuf, count: int, root: int) -> None:
        """gather within each inner group to the delegate, then gather
        slabs along the root's outer group."""
        if self.flat:
            self.accl.gather(sendbuf, recvbuf, count, root)
            return
        B = len(self._inner_group)
        me = self.accl.rank
        delegate = next(m for m in self._inner_group
                        if self._same_inner_slot(m, root))
        is_delegate = me == delegate
        mid = (self._buf("ga_mid", count * B, sendbuf.dtype)
               if is_delegate else None)
        self.accl.gather(sendbuf, mid, count,
                         self._inner_group.index(delegate),
                         comm_id=self._inner_comm)
        if root in self._outer_group:  # me is one of root's delegates
            self.accl.gather(mid, recvbuf if me == root else None,
                             count * B, self._outer_group.index(root),
                             comm_id=self._outer_comm)

    def _same_inner_slot(self, a: int, b: int) -> bool:
        """True when a and b hold the same inner-axis coordinate (share
        an outer group)."""
        return (self.fabric.coords[a][self._inner_axis]
                == self.fabric.coords[b][self._inner_axis])

    #: collectives this composer can stand in for (the autotuner's
    #: hierarchical lane covers exactly these)
    COMPOSABLE = ("allreduce", "reduce_scatter", "allgather", "bcast",
                  "scatter", "gather")
