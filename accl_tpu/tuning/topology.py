"""Fabric: the topology model the tuner and composer select against.

A :class:`Fabric` is an axis decomposition of one world's ranks — ICI
mesh axes on TPU (from ``utils.topology.probe`` device coords), a
configurable row-major grid for emu worlds (``ACCL_FABRIC=4x2`` or an
explicit ctor shape), or a plain ring (one axis) when nothing better is
known.  It is the ONE source of axis names: ``Fabric.link_axis``
delegates to :func:`accl_tpu.utils.topology.link_axis` with the
fabric's own coords, so the perf_doctor link-matrix rendering and the
tuner's per-axis grouping can never disagree.

``from_link_matrix`` ingests an r15 measured link snapshot
(``world.link_matrix()`` / the perf_doctor link_matrix section) and
scores each axis by the mean ``seek_wait_ns`` + retransmit load of its
links: a measured slow link DEMOTES its axis out of the heavy-traffic
"within" role the hierarchical composer assigns (HiCCL's topology
model role, arxiv 2408.05962).
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

from ..constants import ACCLError
from ..utils import topology as _topo

#: env knob: explicit axis layout for worlds without device coords
#: (emu), e.g. ``ACCL_FABRIC=4x2``; malformed values raise a naming
#: error at Fabric construction (clear-error contract)
FABRIC_ENV = "ACCL_FABRIC"


def _near_square(n: int) -> tuple:
    """Default 2-axis factorization of a world size: the largest factor
    pair (a, b) with a*b == n and a <= b — 8 -> (2, 4), 4 -> (2, 2),
    primes -> (1, n) which is a trivial (single-axis) fabric."""
    best = (1, n)
    a = 1
    while a * a <= n:
        if n % a == 0:
            best = (a, n // a)
        a += 1
    return best


class Fabric:
    """Axis decomposition of ``nranks`` ranks.

    ``shape`` is row-major: rank r has coordinate ``coords[r]`` with
    the LAST axis contiguous in rank order.  ``axis_order`` ranks the
    axes healthiest-first — ``axis_order[0]`` is the axis the composer
    gives the heavy "within" traffic (reduce_scatter + allgather
    stages); measured demotion (:meth:`from_link_matrix`) reorders it.
    """

    def __init__(self, nranks: int, shape: Optional[Sequence[int]] = None,
                 axis_names: Optional[Sequence[str]] = None,
                 axis_order: Optional[Sequence[int]] = None):
        if nranks < 1:
            raise ACCLError(f"Fabric: nranks must be >= 1, got {nranks}")
        if shape is None:
            shape = _near_square(nranks)
        shape = tuple(int(a) for a in shape)
        total = 1
        for a in shape:
            total *= a
        if total != nranks:
            raise ACCLError(
                f"Fabric: axis layout {'x'.join(map(str, shape))} holds "
                f"{total} ranks but the world has {nranks} (set "
                f"{FABRIC_ENV} to a layout whose product is the world "
                f"size)")
        self.nranks = nranks
        self.shape = shape
        self.coords = _topo.grid_coords(nranks, shape)
        names = tuple(axis_names) if axis_names else tuple(
            "xyz"[i] if i < 3 else f"axis{i}" for i in range(len(shape)))
        if len(names) != len(shape):
            raise ACCLError(
                f"Fabric: {len(names)} axis names for {len(shape)} axes")
        self.axis_names = names
        #: healthiest-first; default prefers the LAST (rank-contiguous)
        #: axis for the within role — on TPU meshes that is the
        #: innermost ICI dimension, on emu grids the neighbor links
        self.axis_order = (tuple(axis_order) if axis_order is not None
                           else tuple(reversed(range(len(shape)))))
        if sorted(self.axis_order) != list(range(len(shape))):
            raise ACCLError(
                f"Fabric: axis_order {self.axis_order} is not a "
                f"permutation of the {len(shape)} axes")
        #: axis name -> measured blocked-time score; populated only by
        #: :meth:`from_link_matrix` (empty on unmeasured fabrics)
        self.axis_scores: dict = {}

    # ------------------------------------------------------------------
    # constructors
    # ------------------------------------------------------------------
    @classmethod
    def for_world(cls, nranks: int,
                  shape: Optional[Sequence[int]] = None,
                  probe: bool = True) -> "Fabric":
        """The standard constructor chain: explicit ``shape`` >
        ``ACCL_FABRIC`` env layout > TPU device coords (ICI mesh axes)
        > near-square default factorization.  ``probe=False`` skips the
        device-coord step — it imports jax and touches
        ``jax.devices()``, which on a TPU host CLAIMS the chip (and can
        wedge in the libtpu claim when another process holds it); pure
        offline consumers (perf_doctor rendering a snapshot) must not
        pay that side effect for axis labels."""
        if shape is not None:
            return cls(nranks, shape)
        spec = os.environ.get(FABRIC_ENV, "")
        if spec:
            try:
                return cls(nranks, _topo.parse_shape(spec))
            except ValueError as e:
                raise ACCLError(f"{FABRIC_ENV}={spec!r}: {e}") from e
        coords = cls._probe_coords(nranks) if probe else None
        if coords is not None:
            try:
                return cls.from_coords(nranks, coords)
            except ACCLError:
                # the world does not fill the probed grid (e.g. 3
                # ranks on a 2x2 host): degrade to the factorization
                # fallback instead of refusing a default fabric
                pass
        return cls(nranks)

    @staticmethod
    def _probe_coords(nranks: int):
        """Device ICI coords when jax is up on real hardware; None on
        CPU/interpret rungs (emu worlds have no device coords)."""
        try:
            cap = _topo.probe()
        except Exception:  # noqa: BLE001 — jax may not be importable
            return None
        if cap.platform != "tpu" or len(cap.coords) < nranks:
            return None
        coords = cap.coords[:nranks]
        if any(c is None for c in coords):
            return None
        return [tuple(c) for c in coords]

    @classmethod
    def from_coords(cls, nranks: int, coords: Sequence[tuple]) -> "Fabric":
        """Build from explicit per-rank mesh coordinates (the TPU ICI
        path).  The shape is the per-axis extent; ranks must enumerate
        the grid row-major (jax device order does)."""
        ndim = len(coords[0])
        shape = tuple(max(c[i] for c in coords) + 1 for i in range(ndim))
        fab = cls(nranks, shape)
        if list(map(tuple, coords)) != fab.coords:
            # non-row-major enumeration: keep the explicit coords (the
            # grouping below only needs coord equality, not order)
            fab.coords = [tuple(c) for c in coords]
        return fab

    @classmethod
    def from_link_matrix(cls, matrix: dict,
                         shape: Optional[Sequence[int]] = None,
                         probe: bool = True) -> "Fabric":
        """Build from an r15 measured link snapshot
        (``world.link_matrix()`` schema: ``nranks`` + ``fields`` of P×P
        counter matrices).  Axes are scored by the mean per-link
        ``seek_wait_ns`` (observer-side blocked time) plus a retransmit
        penalty over the links the axis owns; ``axis_order`` lists them
        healthiest-first, so a chaos-slowed or lossy link demotes its
        axis out of the composer's heavy-traffic "within" role."""
        P = int(matrix.get("nranks", 0))
        if P < 1 or "fields" not in matrix:
            raise ACCLError(
                "from_link_matrix: not a link_matrix document (want "
                "the world.link_matrix() / perf_doctor schema with "
                "nranks + fields)")
        fab = cls.for_world(P, shape=shape, probe=probe)
        wait = matrix["fields"].get("seek_wait_ns", [])
        retrans = matrix["fields"].get("retrans_sent", [])
        scores = []
        for axis in range(len(fab.shape)):
            waits, n = 0.0, 0
            for s in range(P):
                for d in range(P):
                    if s == d or fab.axis_of_link(s, d) != axis:
                        continue
                    n += 1
                    if s < len(wait) and d < len(wait[s]):
                        waits += float(wait[s][d])
                    if s < len(retrans) and d < len(retrans[s]):
                        # a retransmit costs at least one RTO round:
                        # weigh it like a millisecond of blocked time
                        waits += 1e6 * float(retrans[s][d])
            scores.append((waits / n if n else 0.0, axis))
        # healthiest (lowest blocked-time) axis first; stable on ties so
        # an unmeasured world keeps the default preference order
        default_pos = {a: i for i, a in enumerate(fab.axis_order)}
        fab.axis_order = tuple(a for _, a in sorted(
            scores, key=lambda sa: (sa[0], default_pos[sa[1]])))
        fab.axis_scores = {fab.axis_names[a]: s for s, a in scores}
        return fab

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------
    @property
    def trivial(self) -> bool:
        """True when there is no second axis to compose across (a
        1-axis fabric or any extent-1 decomposition): the composer
        falls back to the flat driver call."""
        return sum(1 for a in self.shape if a > 1) < 2

    def axis_of_link(self, src: int, dst: int) -> Optional[int]:
        """Index of the single axis src and dst differ on, or None for
        self/multi-axis links."""
        if not (0 <= src < self.nranks and 0 <= dst < self.nranks):
            return None
        diffs = [i for i, (a, b) in
                 enumerate(zip(self.coords[src], self.coords[dst]))
                 if a != b]
        return diffs[0] if len(diffs) == 1 else None

    def link_axis(self, src: int, dst: int) -> str:
        """Axis label of a link — the same names
        :func:`accl_tpu.utils.topology.link_axis` mints from these
        coords (perf_doctor renders with this, the tuner groups with
        it: one source, never two)."""
        return _topo.link_axis(src, dst, coords=self.coords,
                               nranks=self.nranks)

    def groups(self, axis: int) -> list:
        """Partition of the ranks into lines along ``axis``: each group
        varies only the ``axis`` coordinate, sorted by rank, groups
        sorted by their fixed coordinates — the deterministic global
        order every rank iterates when minting sub-communicators."""
        by_key: dict = {}
        for r in range(self.nranks):
            key = tuple(c for i, c in enumerate(self.coords[r])
                        if i != axis)
            by_key.setdefault(key, []).append(r)
        return [sorted(by_key[k]) for k in sorted(by_key)]

    def within_axis(self) -> int:
        """The axis carrying the composer's heavy within-group traffic:
        the healthiest axis with extent > 1."""
        for a in self.axis_order:
            if self.shape[a] > 1:
                return a
        return self.axis_order[0]

    def within_groups(self) -> list:
        """Groups along the within axis (measured demotion moves a slow
        axis out of this role)."""
        return self.groups(self.within_axis())

    def groups_complement(self, axis: int) -> list:
        """The complementary partition of :meth:`groups`: for each
        ``axis`` coordinate, every rank holding it (all other axes
        collapse into one super-group — the two-level composition's
        across stage).  Groups sorted by coordinate, ranks sorted —
        the SAME deterministic order :meth:`groups` uses, because this
        ordering assigns world-wide communicator ids (compose.py)."""
        by_key: dict = {}
        for r in range(self.nranks):
            by_key.setdefault(self.coords[r][axis], []).append(r)
        return [sorted(by_key[k]) for k in sorted(by_key)]

    def across_groups(self) -> list:
        """The complementary partition of the within axis — the groups
        the middle (across) stage reduces over."""
        return self.groups_complement(self.within_axis())

    def spec(self) -> str:
        order = ">".join(self.axis_names[a] for a in self.axis_order)
        return (f"{'x'.join(map(str, self.shape))} "
                f"(axes {','.join(self.axis_names)}; health {order})")

    def __repr__(self) -> str:
        return f"Fabric({self.spec()})"
