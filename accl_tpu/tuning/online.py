"""Online tuner: the live telemetry -> tuner control plane (r19).

:class:`OnlineTuner` closes the loop ROADMAP item 4 left open: the r14
sentinel sees a cell drift, the r15 link matrix sees an axis degrade —
and until now both findings died in a dashboard while the r16
:class:`~accl_tpu.tuning.autotune.SelectionPolicy` kept serving the
table it was armed with at ``initialize``.  The tuner subscribes to
both signals and turns each into a TARGETED hypothesis:

- a sentinel finding on one ``(collective, dtype, size_bucket)`` cell
  re-measures exactly that cell — a quick covering-lane shortlist
  (:func:`~accl_tpu.tuning.autotune.cell_candidates`), then the r16
  interleaved best-of A/B (:func:`~accl_tpu.tuning.autotune.ab_cell`)
  challenger-vs-incumbent in the live session;
- a periodic ``Fabric.from_link_matrix`` re-score whose healthiest-
  first ``axis_order`` changed re-demotes the composer's within axis.

Never a full sweep, and never-slower by construction: a challenger is
installed only when it beats the incumbent by the hysteresis margin in
the interleaved A/B (box drift hits both lanes alike; retry rounds are
symmetric best-of).  A cooldown per cell keeps a noisy box from
thrashing, and a post-install watch auto-REVERTS any selection the
sentinel flags as a regression afterward.

Every install is fenced exactly like abort: a
:data:`~accl_tpu.observability.flight.RETUNE_EVENT` flight anchor,
``ACCL._invalidate_plans(None, ...)`` on every rank (a captured plan
never replays a stale algorithm choice — stale replay raises,
re-capture succeeds), and the backend tuning registers re-derived
through :meth:`SelectionPolicy.hot_swap`.

Observability is first-class: ``tuning/retunes/{proposed,verified,
installed,rejected,reverted}`` counters (METRIC_HELP'd), and a bounded
retune-history ring — every episode's finding -> hypothesis -> A/B ->
decision chain — served at the metrics exporter's ``/retunes``
endpoint and rendered by ``scripts/perf_doctor.py``.

Arming: ``ACCL_TUNE_ONLINE=1`` at world bring-up (EmuWorld/TpuWorld)
starts the loop; unset (the default) constructs NOTHING — dispatch is
bit-identical to the r18 static/table behavior, pinned by
tests/test_online_tuning.py.  Knobs (constants.env_* contract):

================================  =======================================
``ACCL_TUNE_ONLINE``              1 arms the loop (default off)
``ACCL_TUNE_ONLINE_INTERVAL_MS``  loop period (default 5000)
``ACCL_TUNE_ONLINE_COOLDOWN``     per-cell episode cooldown s (def. 30)
``ACCL_TUNE_ONLINE_HYSTERESIS``   install margin ratio (default 1.05)
``ACCL_TUNE_ONLINE_REPS``         A/B repetitions per lane (default 3)
``ACCL_TUNE_ONLINE_HISTORY``      history-ring episodes kept (def. 64)
================================  =======================================

Measurement runs through the world's gang surface, so ``step()`` (and
the background loop) assumes collective QUIESCENCE — the same contract
as running :func:`~accl_tpu.tuning.autotune.tune` against a live
world.  The drill harnesses (tests, scripts/retune_smoke.py) pause
traffic around each step.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Optional

from ..constants import ACCLError, env_float, env_int
from ..observability import flight as _flight
from ..observability import metrics as _metrics
from ..utils.logging import get_logger
from .autotune import (
    SelectionPolicy,
    SelectionTable,
    ab_cell,
    backend_of,
    bucket_bytes,
    cell_candidates,
    cell_key,
)
from .compose import HierarchicalComm
from .topology import Fabric

ENV_ONLINE = "ACCL_TUNE_ONLINE"
ENV_INTERVAL_MS = "ACCL_TUNE_ONLINE_INTERVAL_MS"
ENV_COOLDOWN_S = "ACCL_TUNE_ONLINE_COOLDOWN"
ENV_HYSTERESIS = "ACCL_TUNE_ONLINE_HYSTERESIS"
ENV_REPS = "ACCL_TUNE_ONLINE_REPS"
ENV_HISTORY = "ACCL_TUNE_ONLINE_HISTORY"

HISTORY_FORMAT = "accl-retune-history"
HISTORY_VERSION = 1

#: every decision an episode can end with (the history/doctor schema)
DECISIONS = ("installed", "rejected", "reverted", "cooldown", "error")


def online_enabled() -> bool:
    """One env read: is the online loop armed?  Unset/0/empty = off —
    the caller constructs nothing and dispatch stays bit-identical."""
    return os.environ.get(ENV_ONLINE, "").strip() not in ("", "0")


class RetuneHistory:
    """Bounded ring of retune episodes — the audit trail the exporter
    serves at ``/retunes`` and perf_doctor renders.  Thread-safe; the
    sentinel thread appends triggers while the loop thread closes
    episodes."""

    def __init__(self, maxlen: int = 64):
        self._ring: deque = deque(maxlen=max(int(maxlen), 1))
        self._lock = threading.Lock()
        self._seq = 0
        self.dropped = 0

    def append(self, episode: dict) -> dict:
        with self._lock:
            self._seq += 1
            episode = dict(episode, seq=self._seq)
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(episode)
            return episode

    def episodes(self) -> list:
        with self._lock:
            return [dict(e) for e in self._ring]

    def to_doc(self) -> dict:
        with self._lock:
            return {
                "format": HISTORY_FORMAT,
                "version": HISTORY_VERSION,
                "episodes": [dict(e) for e in self._ring],
                "dropped": self.dropped,
                "total": self._seq,
            }


class OnlineTuner:
    """The control plane for one world: findings in, verified
    selections out, every step audited."""

    def __init__(self, world, *,
                 hysteresis: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 repetitions: Optional[int] = None,
                 retries: int = 2,
                 history: Optional[int] = None,
                 registry=None):
        self.world = world
        self._registry = registry if registry is not None \
            else _metrics.default_registry()
        self.hysteresis = hysteresis if hysteresis is not None \
            else env_float(ENV_HYSTERESIS, 1.05, minimum=1.0)
        self.cooldown_s = cooldown_s if cooldown_s is not None \
            else env_float(ENV_COOLDOWN_S, 30.0, minimum=0.0)
        self.repetitions = repetitions if repetitions is not None \
            else env_int(ENV_REPS, 3, minimum=1)
        self.retries = retries
        self.history = RetuneHistory(
            history if history is not None
            else env_int(ENV_HISTORY, 64, minimum=1))
        self._log = get_logger("accl_tpu.tuning.online")
        self._queue: deque = deque()  # pending finding dicts
        self._queue_lock = threading.Lock()
        self._cooldown: dict = {}  # cell key -> monotonic deadline
        #: installed-cell watch list: key -> {"prev": entry|None,
        #: "installed_at": monotonic, "episode_seq": int}
        self._watch: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._measure_lock = threading.Lock()
        self._sentinel: Any = None
        # one policy per driver, all serving ONE shared table — the
        # armed ACCL_TUNE_TABLE policy when present (adopting its
        # entries as the incumbents), a fresh empty table otherwise
        armed = getattr(world.accls[0], "_tune_policy", None)
        self.table: SelectionTable = armed.table if armed is not None \
            else SelectionTable({}, {
                "nranks": world.nranks,
                "backend": backend_of(world),
                "dtype": "float32",
            })
        for a in world.accls:
            pol = getattr(a, "_tune_policy", None)
            if pol is None:
                a._tune_policy = SelectionPolicy(self.table)
            elif pol.table is not self.table:
                pol.table = self.table
                pol._memo.clear()
        # the fabric the composer serves (axis re-demotion target):
        # the table's tuned-on shape when it carries one, else the
        # same env/probe resolution offline tune() uses (ACCL_FABRIC
        # included — Fabric() alone would silently factorize)
        meta = self.table.world or {}
        fabric: Optional[Fabric] = None
        if meta.get("shape"):
            try:
                fabric = Fabric(
                    world.nranks, shape=meta.get("shape"),
                    axis_order=tuple(meta["axis_order"])
                    if meta.get("axis_order") else None)
            except (ACCLError, KeyError):
                fabric = None
        if fabric is None:
            fabric = Fabric.for_world(
                world.nranks, probe=backend_of(world) == "tpu")
        self.fabric: Fabric = fabric

    # ------------------------------------------------------------------
    # intake
    # ------------------------------------------------------------------
    def attach_sentinel(self, sentinel) -> None:
        """Subscribe to a live sentinel's fresh findings (the check
        thread enqueues; the loop thread measures)."""
        if sentinel is not None:
            sentinel.subscribe(self.on_findings)
            self._sentinel = sentinel

    def on_findings(self, findings: list) -> None:
        """Sentinel subscriber: each fresh finding becomes one pending
        cell hypothesis — or, for a cell installed recently, a revert
        verdict (the selection made things worse: roll it back).
        Findings are stamped on arrival so a finding GENERATED before
        an install can never be mistaken for the install's fallout."""
        now = time.monotonic()
        with self._queue_lock:
            for f in findings:
                self._queue.append(dict(f, _queued_at=now))

    def pending(self) -> int:
        with self._queue_lock:
            return len(self._queue)

    # ------------------------------------------------------------------
    # the loop
    # ------------------------------------------------------------------
    def start(self, interval_s: float = 5.0) -> "OnlineTuner":
        if self._thread is None:
            self.interval_s = max(interval_s, 0.05)
            self._thread = threading.Thread(
                target=self._loop, name="accl-online-tuner", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:  # pragma: no cover — never kill the host
                self._log.warning("online tuner step failed",
                                  exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None
        if self._sentinel is not None:
            self._sentinel.unsubscribe(self.on_findings)
            self._sentinel = None

    def step(self) -> Optional[dict]:
        """One control-plane turn: drain at most one pending finding
        into a retune episode, then re-score the fabric.  Returns the
        episode dict it closed (None when idle).  Tests drive this
        directly; the background loop calls it on the interval."""
        finding = None
        with self._queue_lock:
            if self._queue:
                finding = self._queue.popleft()
        if finding is not None:
            return self._handle_finding(finding)
        return self.rescore_fabric()

    # ------------------------------------------------------------------
    # cell hypotheses
    # ------------------------------------------------------------------
    def _cell_of(self, finding: dict) -> Optional[tuple]:
        """(key, coll, dtype, count) of the table cell a finding names;
        None when the bucket cannot be inverted to a payload."""
        coll = finding.get("collective")
        dtype = finding.get("dtype", "float32")
        bucket = finding.get("size_bucket", "")
        nb = bucket_bytes(bucket)
        if not coll or nb <= 0:
            return None
        from ..bench import sweep as _sweep

        np_dtype = _sweep._resolve_dtype(dtype)
        P = self.world.nranks
        count = nb // (_sweep._payload_factor(coll, P) * np_dtype.itemsize)
        if count < 1:
            return None
        key = cell_key(coll, dtype, bucket, P)
        return key, coll, dtype, int(count)

    def _handle_finding(self, finding: dict) -> dict:
        """finding -> hypothesis -> A/B -> install/reject (or revert,
        when the finding regresses a cell this tuner just installed)."""
        base = {
            "kind": "cell",
            "trigger": {"type": "sentinel", **{
                k: finding.get(k) for k in (
                    "collective", "dtype", "size_bucket", "axis",
                    "ratio", "kind", "live", "baseline")}},
            "opened_at": time.time(),
        }
        cell = self._cell_of(finding)
        if cell is None:
            return self._close(base, "error",
                               reason="finding names no measurable cell")
        key, coll, dtype, count = cell
        base["cell"] = key
        watch = self._watch.get(key)
        if watch is not None:
            if finding.get("_queued_at", 0.0) <= watch["installed_at"]:
                # same-batch sibling of the finding that TRIGGERED the
                # install (e.g. the p50 and busbw axes of one drifted
                # cell arrive together): it predates the install, so
                # it cannot be the install's fallout — drop it
                self._registry.inc("tuning/retunes/rejected")
                return self._close(
                    base, "rejected",
                    reason="stale finding from before the install")
            # post-install regression on a cell we changed: the
            # cross-check the doctor renders — auto-revert, no A/B
            return self._revert(base, key, watch)
        now = time.monotonic()
        if self._cooldown.get(key, 0.0) > now:
            self._registry.inc("tuning/retunes/rejected")
            return self._close(base, "cooldown",
                               reason="cell inside cooldown window")
        self._registry.inc("tuning/retunes/proposed")
        self._cooldown[key] = now + self.cooldown_s
        incumbent_entry = self.table.entries.get(key)
        incumbent = incumbent_entry["algorithm"] if incumbent_entry \
            else "static"
        try:
            with self._measure_lock, self._suspended():
                hier = self._hier_for_measure()
                cands = cell_candidates(
                    self.world, coll, count, dtype,
                    repetitions=min(self.repetitions, 2),
                    hier=hier, exclude=(incumbent,))
                challenger = cands[0][0] if cands else None
                base["hypothesis"] = {
                    "incumbent": incumbent,
                    "challenger": challenger,
                    "shortlist": [
                        {"algorithm": a, "busbw_GBps": b}
                        for a, b in cands],
                }
                if challenger is None:
                    self._registry.inc("tuning/retunes/rejected")
                    return self._close(
                        base, "rejected",
                        reason="no covering challenger lane")
                inc_bw, ch_bw = ab_cell(
                    self.world, incumbent, challenger, coll, count,
                    dtype, repetitions=self.repetitions,
                    retries=self.retries, hier=hier)
        except (ACCLError, ValueError, KeyError) as e:
            self._registry.inc("tuning/retunes/rejected")
            return self._close(base, "error", reason=str(e))
        base["ab"] = {
            "incumbent_busbw_GBps": inc_bw,
            "challenger_busbw_GBps": ch_bw,
            "ratio": round(ch_bw / inc_bw, 3) if inc_bw else 0.0,
        }
        if not inc_bw or ch_bw < inc_bw * self.hysteresis:
            self._registry.inc("tuning/retunes/rejected")
            return self._close(
                base, "rejected",
                reason=f"challenger {ch_bw:.3f} GB/s did not clear "
                       f"incumbent {inc_bw:.3f} x hysteresis "
                       f"{self.hysteresis}")
        self._registry.inc("tuning/retunes/verified")
        entry = {
            "algorithm": challenger,
            "busbw_GBps": ch_bw,
            "static_busbw_GBps":
                inc_bw if incumbent == "static"
                else (incumbent_entry or {}).get("static_busbw_GBps"),
            "bytes": bucket_bytes(finding.get("size_bucket", "")),
            "overlap": None,
            "online": True,
        }
        prev = self._install(key, entry)
        self._registry.inc("tuning/retunes/installed")
        episode = self._close(
            base, "installed",
            reason=f"{challenger} beat {incumbent} "
                   f"{base['ab']['ratio']}x in the interleaved A/B",
            installed=entry)
        self._watch[key] = {"prev": prev,
                            "installed_at": time.monotonic(),
                            "episode_seq": episode.get("seq")}
        return episode

    def _revert(self, base: dict, key: str, watch: dict) -> dict:
        """Roll an installed selection back to its pre-install entry:
        the post-install sentinel window flagged the very cell we
        changed."""
        prev = watch.get("prev")
        self._apply_swap(key, prev, event=_flight.RETUNE_REVERT_EVENT)
        self._watch.pop(key, None)
        # cooldown the cell hard: the box just proved our measurement
        # unrepresentative, so don't immediately re-propose it
        self._cooldown[key] = time.monotonic() + 2 * self.cooldown_s
        self._registry.inc("tuning/retunes/reverted")
        self._log.warning(
            "online retune on %s regressed post-install; reverted to "
            "%s", key,
            (prev or {"algorithm": "static"}).get("algorithm"))
        return self._close(
            base, "reverted",
            reason="post-install sentinel regression on the installed "
                   "cell",
            reverted_to=(prev or {"algorithm": "static"})["algorithm"],
            installed_episode=watch.get("episode_seq"))

    # ------------------------------------------------------------------
    # axis hypotheses (fabric re-score)
    # ------------------------------------------------------------------
    def rescore_fabric(self) -> Optional[dict]:
        """Periodic ``Fabric.from_link_matrix`` re-score: when the
        measured healthiest-first ``axis_order`` differs from the one
        the composer serves, re-demote — update the table's world meta
        (what ``fabric_of_table`` and the transparent ``hier`` dispatch
        compose from) and fence plans + hier memos so the next
        composed call rides the new within axis."""
        if self.fabric.trivial:
            return None
        try:
            matrix = self.world.link_matrix()
            if not any(v for row in matrix["fields"]["seek_wait_ns"]
                       for v in row):
                return None
            fresh = Fabric.from_link_matrix(
                matrix, shape=self.fabric.shape, probe=False)
        except (ACCLError, KeyError, AttributeError):
            return None
        if tuple(fresh.axis_order) == tuple(self.fabric.axis_order):
            return None
        base = {
            "kind": "axis",
            "trigger": {
                "type": "link_matrix",
                "axis_scores": getattr(fresh, "axis_scores", {}),
            },
            "opened_at": time.time(),
            "hypothesis": {
                "axis_order_from": list(self.fabric.axis_order),
                "axis_order_to": list(fresh.axis_order),
            },
        }
        self._registry.inc("tuning/retunes/proposed")
        old_within = self.fabric.within_axis()
        self.fabric = fresh
        meta = dict(self.table.world or {})
        meta["shape"] = list(fresh.shape)
        meta["axis_order"] = list(fresh.axis_order)
        self.table.world = meta
        self._fence_all(_flight.RETUNE_EVENT)
        self._registry.inc("tuning/retunes/installed")
        self._log.warning(
            "measured axis re-demotion: within axis %s -> %s (%s)",
            self.fabric.axis_names[old_within],
            fresh.axis_names[fresh.within_axis()], fresh.spec())
        return self._close(
            base, "installed",
            reason=f"axis health re-ranked: within "
                   f"{fresh.axis_names[old_within]} -> "
                   f"{fresh.axis_names[fresh.within_axis()]}")

    # ------------------------------------------------------------------
    # install plumbing
    # ------------------------------------------------------------------
    def _install(self, key: str, entry: Optional[dict]) -> Optional[dict]:
        prev = self.table.entries.get(key)
        self._apply_swap(key, entry, event=_flight.RETUNE_EVENT)
        return prev

    def _apply_swap(self, key: str, entry: Optional[dict],
                    event: str) -> None:
        """The fenced hot-swap on every rank: flight anchor ->
        plan-ring invalidation (exactly the abort fence: stale replay
        raises, re-capture succeeds) -> register re-derivation through
        the policy -> hier-memo drop."""
        for a in self.world.accls:
            _flight.mark_event(a.flight_recorder, event, -1, 0)
            a._invalidate_plans(None, f"online retune: {key}")
            inv = getattr(a._device, "invalidate_plans", None)
            if inv is not None:
                inv(-1)
            a._tune_policy.hot_swap(a, key, entry)
            a._drop_hier_comms()

    def _fence_all(self, event: str) -> None:
        """The axis-demotion fence: no table cell changed, but every
        captured plan and memoized composition now encodes a stale
        axis assignment."""
        for a in self.world.accls:
            _flight.mark_event(a.flight_recorder, event, -1, 0)
            a._invalidate_plans(None, "online retune: axis re-demotion")
            inv = getattr(a._device, "invalidate_plans", None)
            if inv is not None:
                inv(-1)
            a._tune_policy._memo.clear()
            a._drop_hier_comms()

    # ------------------------------------------------------------------
    # measurement hygiene
    # ------------------------------------------------------------------
    def _suspended(self):
        """Disarm the live policies for the duration of a measurement:
        the A/B must exercise the raw lanes, not route through the
        very policy (or compression/fused default) under test."""
        tuner = self

        class _Suspend:
            def __enter__(self):
                self._stash = [
                    (a, a._tune_policy, a._compress_policy,
                     a._fused_default)
                    for a in tuner.world.accls]
                for a, *_ in self._stash:
                    a._tune_policy = None
                    a._compress_policy = None
                    a._fused_default = False
                    a._call_memo.clear()
                return self

            def __exit__(self, *exc):
                for a, pol, comp, fused in self._stash:
                    a._tune_policy = pol
                    a._compress_policy = comp
                    a._fused_default = fused
                    a._call_memo.clear()
                    if pol is not None:
                        pol._memo.clear()
                return False

        return _Suspend()

    def _hier_for_measure(self) -> Optional[list]:
        """Per-rank composers for hierarchical-lane measurement over
        the CURRENT fabric; None on a trivial fabric (the lane is then
        excluded by cell_candidates)."""
        if self.fabric.trivial:
            return None
        try:
            return [HierarchicalComm(a, self.fabric)
                    for a in self.world.accls]
        except ACCLError:
            return None

    # ------------------------------------------------------------------
    # bookkeeping
    # ------------------------------------------------------------------
    def _close(self, base: dict, decision: str, **fields) -> dict:
        episode = dict(base, decision=decision,
                       closed_at=time.time(), **fields)
        return self.history.append(episode)


# ---------------------------------------------------------------------------
# env-driven singleton (world bring-up arms it next to the sentinel)
# ---------------------------------------------------------------------------
_tuner_lock = threading.Lock()
_tuner: Optional[OnlineTuner] = None


def ensure_online_tuner_from_env(world) -> Optional[OnlineTuner]:
    """Idempotent world-level arm: ``ACCL_TUNE_ONLINE`` unset/0 = off
    (nothing constructed, zero threads, dispatch bit-identical).
    Armed, the tuner subscribes to the env sentinel (when one is
    running) and starts its loop.  Never raises — a tuner fault must
    not take world bring-up down."""
    global _tuner
    if not online_enabled():
        return None
    with _tuner_lock:
        if _tuner is not None:
            return _tuner
        try:
            tuner = OnlineTuner(world)
            from ..observability import sentinel as _sentinel_mod

            tuner.attach_sentinel(_sentinel_mod._sentinel)
            interval = env_int(ENV_INTERVAL_MS, 5000, minimum=1)
            tuner.start(interval / 1000.0)
        except Exception:
            get_logger("accl_tpu.tuning.online").warning(
                "online tuner disabled: bring-up failed", exc_info=True)
            return None
        _tuner = tuner
        return _tuner


def online_tuner() -> Optional[OnlineTuner]:
    return _tuner


def stop_online_tuner() -> None:
    global _tuner
    with _tuner_lock:
        if _tuner is not None:
            _tuner.stop()
            _tuner = None


def history_doc() -> dict:
    """The ``/retunes`` exporter payload: the live tuner's audit ring,
    or an empty document when no tuner is (or ever was) armed."""
    t = _tuner
    if t is None:
        return {"format": HISTORY_FORMAT, "version": HISTORY_VERSION,
                "episodes": [], "dropped": 0, "total": 0}
    return t.history.to_doc()
