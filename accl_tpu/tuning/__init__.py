"""Topology-aware algorithm selection + persistent autotuning (r16).

Three layers (HiCCL, arxiv 2408.05962; ACCL+ crossover points, arxiv
2312.11742 — ROADMAP item 2):

- :mod:`~accl_tpu.tuning.topology` — :class:`Fabric`, the axis model
  over :mod:`accl_tpu.utils.topology`: ICI mesh axes on TPU, a
  configurable ``ACCL_FABRIC=AxB`` layout for emu worlds, and
  ``from_link_matrix`` ingestion of r15 measured per-link traffic so a
  slow link demotes its axis out of the heavy-traffic role.
- :mod:`~accl_tpu.tuning.compose` — :class:`HierarchicalComm`, two-level
  collectives assembled from the existing driver primitives
  (reduce_scatter-within → allreduce-across → allgather-within and the
  scatter/gather/bcast analogues); ordinary driver calls, so a
  composition is capturable with ``ACCL.capture_plan`` and the
  decomposition overhead is paid once per r12 plan.
- :mod:`~accl_tpu.tuning.autotune` — the persistent autotuner: sweeps
  (collective, dtype, size-bucket, world-shape, algorithm) through the
  bench sweep harness, persists a versioned JSON
  :class:`SelectionTable`, and a :class:`SelectionPolicy` the driver
  consults in ``_execute`` — ``Engine::set_tuning`` / the TPU ring
  threshold become the backend of the learned policy.  Knobs:
  ``ACCL_TUNE_TABLE=path`` arms it, ``ACCL_TUNE=0`` restores the static
  thresholds bit-for-bit.
- :mod:`~accl_tpu.tuning.online` — the r19 live control plane:
  :class:`OnlineTuner` subscribes to sentinel findings and link-matrix
  re-scores, re-measures ONE cell (or re-demotes one axis) with the
  interleaved best-of A/B, and hot-swaps the live policy only when the
  challenger wins — never-slower, fenced like abort, every episode in
  the exported retune-history ring.  ``ACCL_TUNE_ONLINE=1`` arms it;
  unset is bit-identical to the static/table dispatch.
"""
from .autotune import (  # noqa: F401
    SelectionPolicy,
    SelectionTable,
    TuneConfig,
    policy_from_env,
    tune,
)
from .compose import HierarchicalComm  # noqa: F401
from .online import (  # noqa: F401
    OnlineTuner,
    RetuneHistory,
    ensure_online_tuner_from_env,
    online_enabled,
    online_tuner,
    stop_online_tuner,
)
from .topology import Fabric  # noqa: F401
