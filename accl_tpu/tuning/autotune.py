"""Persistent autotuner: measured algorithm selection over the fabric.

Sweeps (collective, dtype, size-bucket, world-shape, algorithm) through
the bench sweep harness (:mod:`accl_tpu.bench.sweep` timing/bandwidth
conventions), persists the winners as a versioned JSON
:class:`SelectionTable`, and serves them back through a
:class:`SelectionPolicy`:

- ``install(accl)`` derives the backend's threshold registers from the
  learned table — ``Engine::set_tuning`` flat/tree crossovers on the
  emulator engine, the ring/HLO crossover (``TuningKey.RING_THRESHOLD_
  BYTES``) on the TPU backend — so the static firmware-ported constants
  become the backend of a measured policy;
- ``on_call`` is the driver's per-call consult in ``ACCL._execute``:
  one memoized dict probe per descriptor signature, publishing the
  decision as the ``tuning/selected/<algorithm>`` metric family.

Knobs: ``ACCL_TUNE_TABLE=path`` arms the policy at ``initialize``;
``ACCL_TUNE=0`` disarms it (with both unset nothing is loaded, no
register differs, and dispatch is bit-identical to the static
thresholds).  The ``hierarchical`` lane is served by
:class:`~accl_tpu.tuning.compose.HierarchicalComm` — the composer entry
points (or a captured r12 plan of them); the flat/tree/ring lanes are
register-backed and apply to plain driver calls transparently.
"""
from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Iterable, Optional

import numpy as np

from ..bench import sweep as _sweep
from ..constants import ACCLError, ReduceFunction, TuningKey
from ..observability import metrics as _metrics
from ..utils.logging import get_logger
from .compose import HierarchicalComm
from .topology import Fabric

TABLE_FORMAT = "accl-tune-table"
TABLE_VERSION = 1

#: every algorithm a table may name; per backend only a subset is
#: measurable (see :func:`algorithms_for`).  The compress_* lanes
#: (r17) are the quantized wire widths: the schedule is static's, the
#: payload crosses the wire block-scaled int8 or cast-fp16 — a win in
#: a cell arms the driver's CompressionPolicy at install.
ALGORITHMS = ("static", "flat", "tree", "ring", "hierarchical",
              "compress_int8", "compress_fp16", "fused")

#: collectives the r18 fused (pipelined compute/communication) lane
#: reshapes — the descriptor opt-in routed by backends/tpu.py; a win in
#: a cell means SelectionPolicy should serve ``fused`` for that size
#: bucket under the same never-slower prune as every other lane
FUSED_COLLECTIVES = frozenset(("allreduce", "reduce_scatter"))

#: the measurable compression lanes and their wire dtypes (per-dtype
#: tables: these lanes only cover float32 cells — cell keys already
#: carry the dtype, so the never-slower compare() prune is per dtype
#: by construction)
COMPRESSION_ALGS = ("compress_int8", "compress_fp16")

#: collectives the compression lanes can touch (the CompressionPolicy
#: default set minus p2p; alltoall has no compress_dtype)
COMPRESS_COLLECTIVES = frozenset((
    "allreduce", "reduce_scatter", "allgather", "reduce", "bcast"))


def _compress_dtype_of(alg: str):
    from ..constants import DataType

    return {"compress_int8": DataType.int8,
            "compress_fp16": DataType.float16}[alg]

ENV_TABLE = "ACCL_TUNE_TABLE"
ENV_TUNE = "ACCL_TUNE"

_HUGE = 0x7FFFFFFF

#: r19 overlap objective: lanes within this busbw fraction of the
#: cell's fastest are a TIE, resolved toward the lane that recovered
#: the most MXU time (lowest r18 ``attribution.overlap`` exposed-wire
#: fraction).  2% sits inside best-of-reps measurement noise, so the
#: tie-break never overrules a real bandwidth win.
OVERLAP_TIE_BAND = 0.02

_BUCKET_UNITS = {"B": 1, "KiB": 1 << 10, "MiB": 1 << 20,
                 "GiB": 1 << 30, "TiB": 1 << 40}
_BUCKET_RE = re.compile(r"<=(\d+)(B|KiB|MiB|GiB|TiB)$")


def bucket_bytes(bucket: str) -> int:
    """Invert :func:`metrics.size_bucket`: the bucket label's
    upper-bound payload in bytes — the representative size a targeted
    online re-measure probes.  0 for the degenerate ``0B`` bucket (and
    anything unparseable: the caller skips those cells)."""
    m = _BUCKET_RE.match(bucket)
    return int(m.group(1)) * _BUCKET_UNITS[m.group(2)] if m else 0


@dataclass
class TuneConfig:
    """One tuning run's sweep space (defaults sized for the emu rung)."""

    collectives: tuple = ("allreduce", "reduce_scatter", "allgather",
                          "bcast", "scatter", "gather", "reduce")
    count_pows: Iterable[int] = tuple(range(6, 17, 2))  # 2^6..2^16 elems
    dtype: str = "float32"
    repetitions: int = 3
    root: int = 0
    shape: Optional[tuple] = None  # fabric layout; None = env/probe
    #: demote axes from a measured link matrix before composing
    measured_demotion: bool = True
    algorithms: Optional[tuple] = None  # None = algorithms_for(world)
    extra: dict = field(default_factory=dict)


# ---------------------------------------------------------------------------
# selection table (the persisted artifact)
# ---------------------------------------------------------------------------

def cell_key(coll: str, dtype: str, bucket: str, nranks: int) -> str:
    return f"{coll}|{dtype}|{bucket}|{nranks}"


class SelectionTable:
    """The versioned, machine-specific (collective, dtype, size-bucket,
    world-shape) -> algorithm map the policy serves."""

    def __init__(self, entries: dict, world: dict):
        self.entries = entries
        self.world = world
        self._dtypes: Optional[frozenset] = None
        self._fallback_logged: set = set()

    def dtypes(self) -> frozenset:
        """The dtypes this table has swept cells for (cached; any
        entry mutation must clear ``_dtypes``)."""
        if self._dtypes is None:
            self._dtypes = frozenset(
                k.split("|")[1] for k in self.entries)
        return self._dtypes

    def lookup(self, coll: str, dtype: str, nbytes: int,
               nranks: int) -> Optional[dict]:
        bucket = _metrics.size_bucket(nbytes)
        entry = self.entries.get(cell_key(coll, dtype, bucket, nranks))
        if entry is not None or dtype == "float32":
            return entry
        # per-dtype tables (r19): a dtype the sweep never covered is
        # served the float32 row — the schedule crossovers are shaped
        # by payload bytes, not element type — and logged once so an
        # operator knows the selection is borrowed, not measured
        if dtype in self.dtypes():
            return None  # swept dtype, genuinely untuned cell
        entry = self.entries.get(
            cell_key(coll, "float32", bucket, nranks))
        if entry is not None and dtype not in self._fallback_logged:
            self._fallback_logged.add(dtype)
            get_logger("accl_tpu.tuning").info(
                "selection table has no %s cells; serving the float32 "
                "row (sweep it: scripts/accl_tune.py --dtype %s)",
                dtype, dtype)
        return entry

    def to_doc(self) -> dict:
        return {
            "format": TABLE_FORMAT,
            "version": TABLE_VERSION,
            "world": self.world,
            "entries": self.entries,
        }

    def save(self, path: str) -> None:
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_doc(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)

    @classmethod
    def from_doc(cls, doc: dict, source: str = "<doc>") -> "SelectionTable":
        if not isinstance(doc, dict) or doc.get("format") != TABLE_FORMAT:
            raise ACCLError(
                f"{source}: not a selection table (format="
                f"{doc.get('format') if isinstance(doc, dict) else doc!r};"
                f" want {TABLE_FORMAT!r})")
        version = doc.get("version")
        if version != TABLE_VERSION:
            raise ACCLError(
                f"{source}: selection-table version {version!r} is not "
                f"supported (this build reads version {TABLE_VERSION}; "
                f"re-run scripts/accl_tune.py to regenerate)")
        entries = doc.get("entries")
        if not isinstance(entries, dict):
            raise ACCLError(f"{source}: corrupt selection table — "
                            f"'entries' is {type(entries).__name__}, "
                            f"not a dict")
        for key, e in entries.items():
            if (not isinstance(e, dict)
                    or e.get("algorithm") not in ALGORITHMS
                    or len(key.split("|")) != 4):
                raise ACCLError(
                    f"{source}: corrupt selection-table entry {key!r}: "
                    f"{e!r} (want collective|dtype|bucket|nranks -> "
                    f"{{algorithm in {ALGORITHMS}}})")
        return cls(entries, doc.get("world", {}))

    @classmethod
    def load(cls, path: str) -> "SelectionTable":
        try:
            with open(path) as f:
                doc = json.load(f)
        except OSError as e:
            raise ACCLError(
                f"{ENV_TABLE}={path}: cannot read selection table "
                f"({e})") from e
        except ValueError as e:
            raise ACCLError(
                f"{ENV_TABLE}={path}: corrupt selection table (not "
                f"JSON: {e})") from e
        return cls.from_doc(doc, source=path)


# ---------------------------------------------------------------------------
# algorithm lanes (world-level knob application)
# ---------------------------------------------------------------------------

def backend_of(obj) -> str:
    """'tpu' for the shared-comm-table backend, 'emu' otherwise; works
    for worlds (devices[0]) and drivers (device)."""
    dev = obj.devices[0] if hasattr(obj, "devices") else obj.device
    return "tpu" if getattr(dev, "comm_table_is_shared", False) else "emu"


def algorithms_for(world, dtype: str = "float32") -> tuple:
    """The measurable lanes per backend: the emulator engine's flat vs
    binomial-tree schedule registers (its rendezvous allreduce is
    already ring-based), the TPU backend's ring/HLO crossover, the
    composer on both, and — for float32 cells — the r17 compression
    lanes (the per-dtype-table REMAINING item: other dtypes simply
    have no compressed pair registered)."""
    comp = COMPRESSION_ALGS if dtype == "float32" else ()
    if backend_of(world) == "tpu":
        return ("static", "flat", "ring", "hierarchical", "fused") + comp
    return ("static", "flat", "tree", "hierarchical") + comp


#: which collectives each REGISTER lane can touch at all.  The emu
#: engine consults the flat-tree registers only in bcast / gather /
#: reduce dispatch (engine.cpp tree_bcast/fanin/tree_reduce switches);
#: the TPU ring threshold reshapes only allreduce / allgather /
#: reduce_scatter gang plans.
LANE_COLLECTIVES = {
    ("emu", "flat"): frozenset(("bcast", "gather", "reduce")),
    ("emu", "tree"): frozenset(("bcast", "gather", "reduce")),
    ("tpu", "flat"): frozenset(("allreduce", "allgather",
                                "reduce_scatter")),
    ("tpu", "ring"): frozenset(("allreduce", "allgather",
                                "reduce_scatter")),
}


def _emu_static_decision(coll: str, P: int, wire_bytes: int,
                         regs: dict) -> bool:
    """The emu engine's flat-or-not decision under the given register
    values (mirrors engine.cpp: bcast flat iff P <= max_ranks; reduce
    flat iff P <= max_ranks or bytes <= max_count; gather fan-in
    capped iff bytes > max_count)."""
    if coll == "bcast":
        return P <= regs[int(TuningKey.BCAST_FLAT_TREE_MAX_RANKS)]
    if coll == "reduce":
        return (P <= regs[int(TuningKey.REDUCE_FLAT_TREE_MAX_RANKS)]
                or wire_bytes
                <= regs[int(TuningKey.REDUCE_FLAT_TREE_MAX_COUNT)])
    if coll == "gather":
        # "flat" here = fan-in UNcapped
        return wire_bytes <= regs[
            int(TuningKey.GATHER_FLAT_TREE_MAX_COUNT)]
    return True


def lane_covers(backend: str, alg: str, coll: str,
                nranks: Optional[int] = None,
                nbytes: Optional[int] = None,
                static_regs: Optional[dict] = None) -> bool:
    """True when measuring (alg, coll) — at this world size and cell
    payload, when given — exercises a genuinely DIFFERENT dispatch
    than static.  A lane that resolves to the same schedule as the
    static registers (e.g. the tree lane for bcast at P=4, where
    static's max_ranks=3 already picks the tree) is excluded: the
    argmax would otherwise select between bit-identical code paths on
    timing noise and ship phantom wins."""
    if alg == "static":
        return True
    if alg == "hierarchical":
        return coll in HierarchicalComm.COMPOSABLE
    if alg in COMPRESSION_ALGS:
        # a compressed wire is a genuinely different datapath than
        # static at every size; coverage is by collective only
        return coll in COMPRESS_COLLECTIVES
    if alg == "fused":
        # the chunked pipelined ring is a per-descriptor opt-in (no
        # register resolves to it), so it is a different dispatch than
        # static at every size; only the TPU backend routes it
        return backend == "tpu" and coll in FUSED_COLLECTIVES
    covered = LANE_COLLECTIVES.get((backend, alg))
    if covered is not None and coll not in covered:
        return False
    if nranks is None or nbytes is None:
        return True  # no cell info: keep the coarse answer
    if backend == "tpu":
        # per-rank operand bytes the gang planner compares (table/
        # sweep bytes carry the nccl payload factor: P for allgather)
        per_rank = nbytes // nranks if coll == "allgather" else nbytes
        static_thr = int(os.environ.get("ACCL_RING_THRESHOLD",
                                        str(4 << 20)))
        static_ring = per_rank >= static_thr
        return static_ring != (alg == "ring")
    if static_regs is None:
        return True
    # emu wire bytes: bcast/reduce/gather payload factors are all 1
    # (metrics._XP_COLLECTIVES covers allgather/reduce_scatter/
    # alltoall only), so table/sweep bytes == the per-rank elems*eb
    # the engine's register compares see
    static_flat = _emu_static_decision(coll, nranks, nbytes, static_regs)
    return static_flat != (alg == "flat")


def apply_algorithm(world, alg: str) -> None:
    """Program every rank's registers for one lane.  ``static``
    restores exactly the initialize-time values
    (:meth:`ACCL.static_tuning` / the env ring threshold)."""
    tpu = backend_of(world) == "tpu"
    for a in world.accls:
        if tpu:
            if alg == "flat":
                a.set_tuning(int(TuningKey.RING_THRESHOLD_BYTES), _HUGE)
            elif alg == "ring":
                a.set_tuning(int(TuningKey.RING_THRESHOLD_BYTES), 0)
            else:  # static / hierarchical / fused ride the env default
                # (the fused lane is a per-CALL descriptor opt-in, not
                # a register: _run_once passes fused=True instead)
                a.set_tuning(
                    int(TuningKey.RING_THRESHOLD_BYTES),
                    int(os.environ.get("ACCL_RING_THRESHOLD",
                                       str(4 << 20))))
            continue
        if alg == "flat":
            for key in (TuningKey.BCAST_FLAT_TREE_MAX_RANKS,
                        TuningKey.REDUCE_FLAT_TREE_MAX_RANKS,
                        TuningKey.GATHER_FLAT_TREE_MAX_FANIN,
                        TuningKey.GATHER_FLAT_TREE_MAX_COUNT,
                        TuningKey.REDUCE_FLAT_TREE_MAX_COUNT):
                a.set_tuning(int(key), _HUGE)
        elif alg == "tree":
            for key in (TuningKey.BCAST_FLAT_TREE_MAX_RANKS,
                        TuningKey.REDUCE_FLAT_TREE_MAX_RANKS,
                        TuningKey.REDUCE_FLAT_TREE_MAX_COUNT,
                        TuningKey.GATHER_FLAT_TREE_MAX_COUNT):
                a.set_tuning(int(key), 0)
            a.set_tuning(int(TuningKey.GATHER_FLAT_TREE_MAX_FANIN), 2)
        else:  # static / hierarchical / compress_* measure against the
            a.apply_static_tuning()  # static registers


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------

def _overlap_marks() -> dict:
    """Per-recorder flight-ring seq watermark, taken before a cell's
    timed reps so the overlap column accounts ONLY that cell's calls."""
    from ..observability import flight as _flight

    return {id(r): (r, max((rec.seq for rec in r.records()),
                           default=-1))
            for r in _flight.recorders()}


def _cell_overlap(marks: dict) -> Optional[float]:
    """The measured ``attribution.overlap`` exposed-wire fraction of
    the flight records landed since ``marks`` (one sweep cell), with
    the trace collector's device stamp slices as compute windows when
    ``ACCL_DEVICE_TRACE`` armed them.  None when nothing completed
    (flight recorder off / single-rank view)."""
    from ..observability import attribution as _attr
    from ..observability import flight as _flight
    from ..observability import trace as _trace

    docs = []
    for rec, mark in marks.values():
        d = rec.dump()
        d["records"] = [r for r in d["records"] if r["seq"] > mark]
        docs.append(d)
    if not docs:
        return None
    trace_doc = (_trace.collector().to_perfetto()
                 if _trace.collector().device_records() else None)
    try:
        rep = _attr.overlap(_flight.merge_flight_dumps(docs),
                            trace_doc=trace_doc)
    except (ACCLError, ValueError, KeyError):
        return None
    wire = sum(c["wire_us"] for c in rep["collectives"].values())
    exposed = sum(c["exposed_us"] for c in rep["collectives"].values())
    return round(exposed / wire, 4) if wire > 0 else None


def _run_once_hier(world, hier, coll: str, count: int, dtype,
                   root: int) -> float:
    """One timed hierarchical collective across all ranks (the
    composer twin of bench.sweep._run_once; same buffer discipline and
    max-duration convention)."""
    P = world.nranks

    def body(accl, rank):
        h = hier[rank]
        made = []

        def mk(factory, *args):
            buf = factory(*args)
            made.append(buf)
            return buf

        data = np.full(count, rank + 1, dtype)
        try:
            if coll == "allreduce":
                send = mk(accl.create_buffer_like, data)
                recv = mk(accl.create_buffer, count, dtype)
                t0 = time.perf_counter()
                h.allreduce(send, recv, count, ReduceFunction.SUM)
                return time.perf_counter() - t0
            if coll == "reduce_scatter":
                send = mk(accl.create_buffer_like, np.tile(data, P))
                recv = mk(accl.create_buffer, count, dtype)
                t0 = time.perf_counter()
                h.reduce_scatter(send, recv, count, ReduceFunction.SUM)
                return time.perf_counter() - t0
            if coll == "allgather":
                send = mk(accl.create_buffer_like, data)
                recv = mk(accl.create_buffer, count * P, dtype)
                t0 = time.perf_counter()
                h.allgather(send, recv, count)
                return time.perf_counter() - t0
            if coll == "bcast":
                buf = mk(accl.create_buffer_like, data)
                t0 = time.perf_counter()
                h.bcast(buf, count, root)
                return time.perf_counter() - t0
            if coll == "scatter":
                send = mk(accl.create_buffer_like, np.tile(data, P))
                recv = mk(accl.create_buffer, count, dtype)
                t0 = time.perf_counter()
                h.scatter(send, recv, count, root)
                return time.perf_counter() - t0
            if coll == "gather":
                send = mk(accl.create_buffer_like, data)
                recv = mk(accl.create_buffer, count * P, dtype)
                t0 = time.perf_counter()
                h.gather(send, recv, count, root)
                return time.perf_counter() - t0
            raise ACCLError(f"hierarchical lane has no {coll!r}")
        finally:
            for buf in made:
                free = getattr(buf, "free", None)
                if free is not None:
                    free()

    return max(world.run(body))


def measure(world, config: TuneConfig = TuneConfig(),
            fabric: Optional[Fabric] = None,
            hier: Optional[list] = None,
            log=None) -> list:
    """Sweep every lane x cell; returns rows with the bench sweep's
    bandwidth conventions plus an ``algorithm`` column (best-of-reps:
    shared-core noise would otherwise thrash the argmax)."""
    P = world.nranks
    dtype = _sweep._resolve_dtype(config.dtype)
    algs = config.algorithms or algorithms_for(world, config.dtype)
    own_hier = False
    if "hierarchical" in algs and hier is None:
        fabric = fabric or Fabric.for_world(
            P, shape=config.shape,
            probe=backend_of(world) == "tpu")
        hier = ([HierarchicalComm(a, fabric) for a in world.accls]
                if not fabric.trivial else None)
        own_hier = hier is not None
    backend = backend_of(world)
    static_regs = world.accls[0].static_tuning()
    rows = []
    try:
        for alg in algs:
            apply_algorithm(world, alg)
            for coll in config.collectives:
                if alg == "hierarchical" and hier is None:
                    continue
                for pw in config.count_pows:
                    count = 1 << pw
                    nbytes = (count * _sweep._payload_factor(coll, P)
                              * dtype.itemsize)
                    if not lane_covers(backend, alg, coll, nranks=P,
                                       nbytes=nbytes,
                                       static_regs=static_regs):
                        continue

                    def run(coll=coll, count=count):
                        if alg == "hierarchical":
                            return _run_once_hier(world, hier, coll,
                                                  count, dtype,
                                                  config.root)
                        if alg in COMPRESSION_ALGS:
                            return _sweep._run_once(
                                world, coll, count, dtype, config.root,
                                compress=_compress_dtype_of(alg))
                        if alg == "fused":
                            return _sweep._run_once(world, coll, count,
                                                    dtype, config.root,
                                                    fused=True)
                        return _sweep._run_once(world, coll, count,
                                                dtype, config.root)

                    run()  # untimed warmup (jit/compile/path setup)
                    marks = _overlap_marks()
                    dur = min(run() for _ in range(config.repetitions))
                    algbw = nbytes / dur / 1e9 if dur > 0 else 0.0
                    rows.append({
                        "algorithm": alg,
                        "collective": coll,
                        # r19 per-dtype tables: rows carry their own
                        # dtype so one table can merge multiple sweeps
                        "dtype": config.dtype,
                        "count": count,
                        "bytes": nbytes,
                        "size_bucket": _metrics.size_bucket(nbytes),
                        "duration_us": round(dur * 1e6, 2),
                        "busbw_GBps": round(
                            algbw * _sweep._busbw_factor(coll, P), 4),
                        # r18: measured exposed-wire fraction of this
                        # cell's reps (attribution.overlap)
                        "overlap": _cell_overlap(marks),
                    })
                    if log:
                        r = rows[-1]
                        log(f"  {alg:>12} {coll:<14} {count:>8} elems "
                            f"{r['duration_us']:>10.1f} us "
                            f"{r['busbw_GBps']:>8.3f} GB/s")
    finally:
        apply_algorithm(world, "static")
        if own_hier and hier is not None:
            for h in hier:
                h.close()  # drop cached scratch; sub-comms stay (ids
                # are burned either way — the create-order discipline)
    return rows


def _tie_rank(r: dict) -> tuple:
    """Ordering within a busbw tie band: most recovered MXU fraction
    (1 - overlap) first, then raw busbw, then static (ties on a box
    with no flight coverage keep the pre-r19 argmax winner)."""
    ov = r.get("overlap")
    recovered = (1.0 - ov) if ov is not None else -1.0
    return (recovered, r["busbw_GBps"], r["algorithm"] == "static")


def build_table(rows: list, world_meta: dict) -> SelectionTable:
    """Per-cell argmax busbw over the measured lanes.  ``static`` is
    always a candidate, so a tuned world is never knowingly worse than
    the static thresholds on any measured cell.  Lanes within
    ``OVERLAP_TIE_BAND`` of the fastest are tie-broken toward the one
    with the lowest measured exposed-wire fraction (r18
    ``attribution.overlap`` folded into the objective): equal wire
    speed, more MXU time recovered."""
    cells: dict = {}
    for r in rows:
        key = cell_key(r["collective"],
                       r.get("dtype")
                       or world_meta.get("dtype", "float32"),
                       r["size_bucket"], world_meta["nranks"])
        cells.setdefault(key, []).append(r)
    entries = {}
    for key, cands in cells.items():
        top = max(cands, key=lambda r: r["busbw_GBps"])
        band = [r for r in cands if r["busbw_GBps"]
                >= top["busbw_GBps"] * (1.0 - OVERLAP_TIE_BAND)]
        best = max(band, key=_tie_rank) if len(band) > 1 else top
        static = next((r for r in cands if r["algorithm"] == "static"),
                      None)
        entries[key] = {
            "algorithm": best["algorithm"],
            "busbw_GBps": best["busbw_GBps"],
            "static_busbw_GBps":
                static["busbw_GBps"] if static else None,
            "bytes": best["bytes"],
            # r18: the winner's measured exposed-wire fraction (None
            # when the cell had no flight coverage)
            "overlap": best.get("overlap"),
        }
    return SelectionTable(entries, world_meta)


def tune(world, config: TuneConfig = TuneConfig(), log=None,
         ) -> SelectionTable:
    """The full pipeline: fabric (with measured demotion when the world
    has r15 link counters) -> lane sweep -> argmax table."""
    fabric = None
    if config.measured_demotion:
        try:
            matrix = world.link_matrix()
            if any(v for row in matrix["fields"]["seek_wait_ns"]
                   for v in row):
                fabric = Fabric.from_link_matrix(
                    matrix, shape=config.shape,
                    probe=backend_of(world) == "tpu")
                if log:
                    log(f"fabric from measured links: {fabric.spec()}")
        except (ACCLError, KeyError, AttributeError):
            fabric = None
    if fabric is None:
        fabric = Fabric.for_world(world.nranks, shape=config.shape,
                                  probe=backend_of(world) == "tpu")
        if log:
            log(f"fabric: {fabric.spec()}")
    rows = measure(world, config, fabric=fabric, log=log)
    meta = {
        "nranks": world.nranks,
        "shape": list(fabric.shape),
        "axis_order": list(fabric.axis_order),
        "backend": backend_of(world),
        "dtype": config.dtype,
    }
    return build_table(rows, meta)


def fabric_of_table(table: SelectionTable, nranks: int,
                    fallback_shape=None) -> Fabric:
    """Rebuild the fabric a table was tuned on from its persisted world
    meta (shape + axis_order), so verification and serving compose the
    SAME way tune() measured — including measured axis demotion."""
    meta = table.world or {}
    shape = meta.get("shape") or fallback_shape
    order = meta.get("axis_order")
    try:
        return Fabric(nranks, shape=shape,
                      axis_order=tuple(order) if order else None)
    except ACCLError:
        # fallback only: never pay a device probe (and its libtpu
        # claim) for a table that failed to carry its own shape
        return Fabric.for_world(nranks, shape=fallback_shape,
                                probe=False)


def compare(world, table: SelectionTable,
            config: TuneConfig = TuneConfig(), log=None,
            prune: bool = True, retries: int = 2,
            fabric: Optional[Fabric] = None,
            hier: Optional[list] = None) -> list:
    """Static vs tuned verification rows (the committed
    ``sweep_rNN_tuned_vs_static`` record): re-measures each table cell
    under the static registers and under the table's chosen lane —
    INTERLEAVED rep pairs in the same session, best-of per lane, so
    box drift hits both lanes alike — and reports the busbw ratio.

    With ``prune`` (the default), a selection that cannot reproduce
    its win within ``retries`` fresh measurement rounds is DEMOTED to
    ``static`` in the table itself: the tuner refuses to ship a
    selection it cannot verify, so a verified table is never slower
    than static on any measured cell by construction."""
    P = world.nranks
    if fabric is None:
        # the fabric the table was MEASURED on (incl. demotion), not a
        # fresh default — verifying a different composition would prune
        # every demoted-fabric win as unreproducible
        fabric = fabric_of_table(table, P, fallback_shape=config.shape)
    own_hier = False
    if hier is None and not fabric.trivial:
        hier = [HierarchicalComm(a, fabric) for a in world.accls]
        own_hier = True
    dtype = _sweep._resolve_dtype(config.dtype)
    out = []
    for key, entry in sorted(table.entries.items()):
        coll, dt, bucket, nranks = key.split("|")
        if int(nranks) != P or dt != config.dtype:
            continue
        count = int(entry["bytes"] // (_sweep._payload_factor(coll, P)
                                       * dtype.itemsize))
        alg = entry["algorithm"]
        nbytes = (count * _sweep._payload_factor(coll, P)
                  * dtype.itemsize)
        if (alg == "hierarchical" and hier is None) or not lane_covers(
                backend_of(world), alg, coll, nranks=P, nbytes=nbytes,
                static_regs=world.accls[0].static_tuning()):
            alg = "static"
        bwf = _sweep._busbw_factor(coll, P)

        def run_lane(lane):
            if lane == "hierarchical":
                apply_algorithm(world, "static")
                return _run_once_hier(world, hier, coll, count, dtype,
                                      config.root)
            if lane in COMPRESSION_ALGS:
                apply_algorithm(world, "static")
                return _sweep._run_once(world, coll, count, dtype,
                                        config.root,
                                        compress=_compress_dtype_of(lane))
            if lane == "fused":
                apply_algorithm(world, "static")
                return _sweep._run_once(world, coll, count, dtype,
                                        config.root, fused=True)
            apply_algorithm(world, lane)
            return _sweep._run_once(world, coll, count, dtype,
                                    config.root)

        def to_bw(dur):
            return round(nbytes / dur / 1e9 * bwf, 4) if dur > 0 else 0.0

        def measure_pair():
            run_lane("static"), run_lane(alg)  # warm both lanes
            ds, dt_ = [], []
            for _ in range(config.repetitions):
                ds.append(run_lane("static"))
                dt_.append(run_lane(alg))
            return to_bw(min(ds)), to_bw(min(dt_))

        if alg == "static":
            static_bw = tuned_bw = measure_pair()[0]
        else:
            static_bw, tuned_bw = measure_pair()
            attempts = retries
            while tuned_bw < static_bw and attempts > 0:
                attempts -= 1
                s2, t2 = measure_pair()
                # symmetric best-of across rounds: both lanes keep
                # their best showing, so retrying cannot bias the
                # ratio toward either side
                static_bw = max(static_bw, s2)
                tuned_bw = max(tuned_bw, t2)
            if prune and tuned_bw < static_bw:
                # unreproducible win: ship static for this cell
                table.entries[key] = dict(
                    entry, algorithm="static",
                    busbw_GBps=entry.get("static_busbw_GBps")
                    or static_bw, pruned_from=alg)
                alg, tuned_bw = "static", static_bw
        ratio = round(tuned_bw / static_bw, 3) if static_bw else 0.0
        out.append({
            "collective": coll,
            "dtype": dt,
            "size_bucket": bucket,
            "count": count,
            "bytes": nbytes,
            "algorithm": alg,
            "static_busbw_GBps": static_bw,
            "tuned_busbw_GBps": tuned_bw,
            "ratio": ratio,
        })
        if log:
            log(f"  {coll:<14} {bucket:>9} {alg:>12}: static "
                f"{static_bw:8.3f} tuned {tuned_bw:8.3f} GB/s "
                f"({ratio}x)")
    apply_algorithm(world, "static")
    if own_hier and hier is not None:
        for h in hier:
            h.close()
    return out


# ---------------------------------------------------------------------------
# single-cell measurement (the online tuner's unit of work)
# ---------------------------------------------------------------------------

def run_cell_lane(world, alg: str, coll: str, count: int, dtype,
                  root: int = 0, hier: Optional[list] = None) -> float:
    """One timed rep of one lane on one cell — ``compare()``'s
    run-lane contract at module level so the online tuner re-measures
    exactly the way the offline verifier did."""
    if alg == "hierarchical":
        apply_algorithm(world, "static")
        return _run_once_hier(world, hier, coll, count, dtype, root)
    if alg in COMPRESSION_ALGS:
        apply_algorithm(world, "static")
        return _sweep._run_once(world, coll, count, dtype, root,
                                compress=_compress_dtype_of(alg))
    if alg == "fused":
        apply_algorithm(world, "static")
        return _sweep._run_once(world, coll, count, dtype, root,
                                fused=True)
    apply_algorithm(world, alg)
    return _sweep._run_once(world, coll, count, dtype, root)


def cell_candidates(world, coll: str, count: int,
                    dtype_name: str = "float32", *,
                    repetitions: int = 2, root: int = 0,
                    hier: Optional[list] = None,
                    exclude: tuple = ()) -> list:
    """Quick best-of sweep of every covering lane on ONE cell — the
    online tuner's challenger shortlist (a targeted hypothesis, never
    a full sweep).  Returns ``[(algorithm, busbw_GBps)]`` fastest
    first; registers are restored to static."""
    P = world.nranks
    dtype = _sweep._resolve_dtype(dtype_name)
    nbytes = count * _sweep._payload_factor(coll, P) * dtype.itemsize
    bwf = _sweep._busbw_factor(coll, P)
    static_regs = world.accls[0].static_tuning()
    backend = backend_of(world)
    out = []
    try:
        for alg in algorithms_for(world, dtype_name):
            if alg in exclude:
                continue
            if alg == "hierarchical" and hier is None:
                continue
            if not lane_covers(backend, alg, coll, nranks=P,
                               nbytes=nbytes, static_regs=static_regs):
                continue
            run_cell_lane(world, alg, coll, count, dtype, root, hier)
            dur = min(run_cell_lane(world, alg, coll, count, dtype,
                                    root, hier)
                      for _ in range(repetitions))
            bw = round(nbytes / dur / 1e9 * bwf, 4) if dur > 0 else 0.0
            out.append((alg, bw))
    finally:
        apply_algorithm(world, "static")
    return sorted(out, key=lambda t: -t[1])


def ab_cell(world, incumbent: str, challenger: str, coll: str,
            count: int, dtype_name: str = "float32", *,
            repetitions: int = 3, retries: int = 2, root: int = 0,
            hier: Optional[list] = None) -> tuple:
    """The r16 interleaved best-of A/B on ONE cell: warm both lanes,
    interleave rep pairs in the same session so box drift hits both
    alike, symmetric best-of across retry rounds (retrying cannot bias
    the ratio toward either side).  Returns ``(incumbent_busbw,
    challenger_busbw)`` in GB/s; registers end restored to static."""
    P = world.nranks
    dtype = _sweep._resolve_dtype(dtype_name)
    nbytes = count * _sweep._payload_factor(coll, P) * dtype.itemsize
    bwf = _sweep._busbw_factor(coll, P)

    def to_bw(dur):
        return round(nbytes / dur / 1e9 * bwf, 4) if dur > 0 else 0.0

    def pair():
        run_cell_lane(world, incumbent, coll, count, dtype, root, hier)
        run_cell_lane(world, challenger, coll, count, dtype, root, hier)
        di, dc = [], []
        for _ in range(repetitions):
            di.append(run_cell_lane(world, incumbent, coll, count,
                                    dtype, root, hier))
            dc.append(run_cell_lane(world, challenger, coll, count,
                                    dtype, root, hier))
        return to_bw(min(di)), to_bw(min(dc))

    try:
        inc_bw, ch_bw = pair()
        attempts = retries
        while ch_bw <= inc_bw and attempts > 0:
            attempts -= 1
            i2, c2 = pair()
            inc_bw = max(inc_bw, i2)
            ch_bw = max(ch_bw, c2)
    finally:
        apply_algorithm(world, "static")
    return inc_bw, ch_bw


# ---------------------------------------------------------------------------
# the driver-facing policy
# ---------------------------------------------------------------------------

class SelectionPolicy:
    """Serves a loaded table to one driver: threshold derivation at
    install, a memoized per-descriptor consult on the hot path."""

    _MISS = object()

    def __init__(self, table: SelectionTable):
        self.table = table
        self._memo: dict = {}

    def algorithm_for(self, coll: str, dtype: str, nbytes: int,
                      nranks: int) -> Optional[str]:
        entry = self.table.lookup(coll, dtype, nbytes, nranks)
        return entry["algorithm"] if entry else None

    def _cells(self, coll: str, nranks: int) -> list:
        out = []
        for key, e in self.table.entries.items():
            c, _dt, _b, n = key.split("|")
            if c == coll and int(n) == nranks:
                out.append(e)
        return out

    def install(self, accl) -> None:
        """Program the learned crossovers over the static registers —
        ``Engine::set_tuning`` (emu flat/tree) and the TPU ring
        threshold become the backend of the measured policy.  Cells
        the registers cannot express (``hierarchical``) are served by
        the composer entry points and only recorded here; cells won by
        a compress_* lane arm the driver's CompressionPolicy (the wire
        dtype with the most winning cells, thresholded at the smallest
        winning payload, scoped to the winning collectives).  An
        explicit ACCL_COMPRESS env knob overrides this (the driver
        arms it after the table install)."""
        self._install_compression(accl)
        nranks = accl.size
        if backend_of(accl) == "tpu":
            # convert table payload bytes to the units the gang planner
            # compares (in_len * itemsize): table bytes carry the
            # nccl-tests payload factor — P for allgather (whose ring
            # decision sees the PER-RANK operand), 1-equivalent for
            # allreduce/reduce_scatter (factor 1 / in_len already x P)
            ring_bytes = []
            for coll, div in (("allreduce", 1), ("reduce_scatter", 1),
                              ("allgather", nranks)):
                ring_bytes += [e["bytes"] // div
                               for e in self._cells(coll, nranks)
                               if e["algorithm"] == "ring"]
            if ring_bytes:
                accl.set_tuning(int(TuningKey.RING_THRESHOLD_BYTES),
                                int(min(ring_bytes)))
            return
        regs = {
            "reduce": (TuningKey.REDUCE_FLAT_TREE_MAX_RANKS,
                       TuningKey.REDUCE_FLAT_TREE_MAX_COUNT),
            "gather": (None, TuningKey.GATHER_FLAT_TREE_MAX_COUNT),
            "bcast": (TuningKey.BCAST_FLAT_TREE_MAX_RANKS, None),
        }
        for coll, (ranks_key, count_key) in regs.items():
            cells = self._cells(coll, nranks)
            flat = [e["bytes"] for e in cells
                    if e["algorithm"] == "flat"]
            tree = [e["bytes"] for e in cells
                    if e["algorithm"] == "tree"]
            if not flat and not tree:
                continue  # static/hierarchical everywhere: regs stand
            if count_key is not None:
                # flat at or below the largest flat-winning payload,
                # tree above it; the ranks register defers to the
                # size crossover
                accl.set_tuning(int(count_key),
                                int(max(flat)) if flat else 0)
                if ranks_key is not None:
                    accl.set_tuning(int(ranks_key),
                                    _HUGE if (flat and not tree) else 0)
            elif ranks_key is not None:
                # no size register (bcast): majority vote
                accl.set_tuning(int(ranks_key),
                                _HUGE if len(flat) >= len(tree) else 0)

    def _install_compression(self, accl) -> None:
        from ..arithconfig import CompressionPolicy
        from ..constants import Operation

        nranks = accl.size
        wins: dict = {}
        for key, e in self.table.entries.items():
            coll, dt, _b, n = key.split("|")
            if int(n) != nranks or e.get("algorithm") \
                    not in COMPRESSION_ALGS or dt != "float32":
                continue
            wins.setdefault(e["algorithm"], []).append((coll, e))
        if not wins:
            return
        alg = max(wins, key=lambda a: len(wins[a]))
        cells = wins[alg]
        # table bytes carry the nccl payload factor (P x for allgather
        # AND reduce_scatter/alltoall); the policy thresholds on the
        # DESCRIPTOR payload (count x elem size), so divide it back out
        accl.set_compression(CompressionPolicy(
            dtype=_compress_dtype_of(alg),
            min_bytes=int(min(
                e["bytes"] // _metrics.payload_factor(c, nranks)
                for c, e in cells)),
            collectives=frozenset(int(Operation[c]) for c, _e in cells),
        ))

    def on_call(self, accl, call) -> Optional[str]:
        """The ``_execute`` consult: one memoized dict probe per
        descriptor signature.  First sight of a signature resolves the
        table cell and publishes ``tuning/selected/<algorithm>``."""
        key = (call.scenario, call.arithcfg, call.count, call.comm)
        alg = self._memo.get(key, self._MISS)
        if alg is not self._MISS:
            return alg
        try:
            # the driver's one descriptor-signature derivation — the
            # table is trained on metrics keyed exactly this way
            op, nranks, _rank, dtype, nbytes = \
                accl.resolve_call_signature(call)
            alg = self.algorithm_for(op.name, dtype, nbytes, nranks)
        except (ACCLError, ValueError, KeyError):
            alg = None
        if alg and _metrics.enabled():
            _metrics.default_registry().inc(f"tuning/selected/{alg}")
        self._memo[key] = alg
        return alg

    def hot_swap(self, accl, key: str,
                 entry: Optional[dict]) -> Optional[dict]:
        """The online tuner's install primitive: replace (or drop,
        ``entry=None``) ONE table cell, clear the dispatch memo, and
        re-derive the backend registers from scratch.  Returns the
        previous entry — the caller's revert token.  Registers are
        rebuilt from the static values first because ``install`` only
        writes thresholds it has wins for: a revert that removes the
        last ring/flat win must fall back to static, not keep a stale
        tuned threshold."""
        prev = self.table.entries.get(key)
        if entry is None:
            self.table.entries.pop(key, None)
        else:
            self.table.entries[key] = dict(entry)
        self.table._dtypes = None
        self._memo.clear()
        accl.apply_static_tuning()
        had_compression = accl.compression_policy
        self.install(accl)
        if os.environ.get("ACCL_COMPRESS", "").strip():
            # the env knob outranks table-derived compression at
            # initialize; keep that precedence across an online swap
            accl.set_compression(had_compression)
        elif accl.compression_policy is had_compression and not any(
                e.get("algorithm") in COMPRESSION_ALGS
                for e in self.table.entries.values()):
            # _install_compression leaves an armed policy standing
            # when the swap removed the last compress win — disarm it
            accl.set_compression(None)
        return prev


def policy_from_env() -> Optional[SelectionPolicy]:
    """The initialize-time arm: ``ACCL_TUNE_TABLE`` names a table and
    ``ACCL_TUNE`` != 0.  Both unset -> None (static behavior,
    bit-for-bit); a named-but-unreadable/corrupt table raises the
    naming ACCLError instead of silently running static."""
    if os.environ.get(ENV_TUNE, "1") == "0":
        return None
    path = os.environ.get(ENV_TABLE, "")
    if not path:
        return None
    return SelectionPolicy(SelectionTable.load(path))
