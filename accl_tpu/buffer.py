"""Buffer hierarchy: paired host/device storage with sync and slicing.

Equivalent of the reference buffer stack — abstract `BaseBuffer` with
`sync_to_device` / `sync_from_device` / `slice`, concretized per backend
(reference: driver/xrt/include/accl/buffer.hpp:32-226; FPGABuffer =
XRT BO + host map, fpgabuffer.hpp; SimBuffer mirrors via ZMQ mem writes,
simbuffer.hpp; DummyBuffer stands in for absent operands, dummybuffer.hpp).

TPU-native mapping:
- `EmuBuffer`   — host numpy array mirrored into the native emulator's
                  device memory at an allocated offset (SimBuffer analog).
- `TpuBuffer`   — host numpy array paired with a jax.Array placed on the
                  mesh (FPGABuffer analog; defined in backends/tpu.py).
- `DummyBuffer` — address-0 placeholder substituted for absent operands
                  (reference: accl.cpp prepare_call dummy substitution).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .arithconfig import NUMPY_TO_DATATYPE
from .constants import DataType


class BaseBuffer:
    """A typed span of host memory paired with a device residence.

    `address` is the backend-specific device address (emulator devicemem
    offset, or an opaque handle for the TPU backend) carried in call
    descriptor words 9-14.
    """

    def __init__(self, host: np.ndarray, address: int = 0):
        if host.ndim != 1:
            host = host.reshape(-1)
        self._host = host
        self._address = address

    # -- geometry -----------------------------------------------------
    @property
    def host(self) -> np.ndarray:
        return self._host

    @property
    def address(self) -> int:
        return self._address

    @property
    def length(self) -> int:
        """Element count."""
        return int(self._host.size)

    @property
    def size(self) -> int:
        """Byte count."""
        return int(self._host.nbytes)

    @property
    def dtype(self) -> np.dtype:
        return self._host.dtype

    @property
    def data_type(self) -> DataType:
        return NUMPY_TO_DATATYPE[self._host.dtype]

    @property
    def is_dummy(self) -> bool:
        return False

    @property
    def is_host_only(self) -> bool:
        """True for buffers resident in host memory that the engine
        reaches over the host path (reference: Buffer::is_host_only,
        buffer.hpp; the external_dma / OP0_HOST..RES_HOST move flags,
        ccl_offload_control.h:128-138)."""
        return False

    # -- data movement ------------------------------------------------
    def sync_to_device(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def sync_from_device(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def slice(self, start: int, end: int) -> "BaseBuffer":
        """A sub-span sharing host storage, with device address advanced by
        the byte offset (reference: buffer.hpp slice())."""
        raise NotImplementedError

    def free(self) -> None:
        """Release the device residence (reference: Buffer::free_buffer,
        buffer.hpp).  Backends with an allocator override this
        (EmuBuffer, LintBuffer); for the rest it is a no-op so
        lifecycle-conscious user code — the kind the collective
        sanitizer's use-after-free checker audits — stays portable."""

    def byte_range(self, count: Optional[int] = None) -> tuple:
        """``(address, nbytes)`` of the first `count` elements (whole
        buffer by default) — the operand extent the sanitizer's overlap
        checks reason about."""
        n = self.length if count is None else count
        return (self._address, n * int(self._host.itemsize))

    # -- convenience --------------------------------------------------
    def __len__(self) -> int:
        return self.length

    def __getitem__(self, idx):
        return self._host[idx]

    def __setitem__(self, idx, val):
        self._host[idx] = val

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}(len={self.length}, dtype={self.dtype}, "
            f"addr={self._address:#x})"
        )


class DummyBuffer(BaseBuffer):
    """Placeholder for an absent operand; address 0, no data movement
    (reference: dummybuffer.hpp)."""

    def __init__(self, dtype=np.float32):
        super().__init__(np.zeros(0, dtype=dtype), address=0)

    @property
    def is_dummy(self) -> bool:
        return True

    def sync_to_device(self) -> None:
        pass

    def sync_from_device(self) -> None:
        pass

    def slice(self, start: int, end: int) -> "DummyBuffer":
        return self


class EmuBuffer(BaseBuffer):
    """Host numpy array mirrored into the native emulator's device memory.

    The emulator owns a flat per-rank device memory (the reference
    emulator's `vector<char> devicemem`, test/model/emulator/cclo_emu.cpp:57);
    sync copies bytes across the ctypes boundary like the reference
    SimBuffer's ZMQ mem read/write (simbuffer.hpp).
    """

    def __init__(self, host: np.ndarray, device, address: int, owner: bool = True,
                 host_only: bool = False):
        super().__init__(host, address)
        self._device = device
        self._owner = owner
        self._host_only = host_only

    @property
    def is_host_only(self) -> bool:
        return self._host_only

    def sync_to_device(self) -> None:
        self._device.write_mem(self._address, self._host.tobytes())

    def sync_from_device(self) -> None:
        raw = self._device.read_mem(self._address, self.size)
        self._host[:] = np.frombuffer(raw, dtype=self._host.dtype)

    def slice(self, start: int, end: int) -> "EmuBuffer":
        itemsize = self._host.itemsize
        return EmuBuffer(
            self._host[start:end],
            self._device,
            self._address + start * itemsize,
            owner=False,
            host_only=self._host_only,
        )

    def free(self) -> None:
        if self._owner:
            self._device.free_mem(self._address)


class EmuBufferP2P(EmuBuffer):
    """Peer-addressable emulator buffer (reference: FPGABufferP2P,
    fpgabufferp2p.hpp — a p2p BO whose host pointer IS device memory via
    bo.map).  `host` here is a numpy view directly over the engine's
    devicemem span, so syncs are no-ops; the span is registered
    peer-writable and an in-process peer's rendezvous write lands in it
    bypassing the wire (native engine rndzv_send fast path)."""

    def sync_to_device(self) -> None:
        pass  # the host view IS the device memory

    def sync_from_device(self) -> None:
        pass

    def slice(self, start: int, end: int) -> "EmuBufferP2P":
        itemsize = self._host.itemsize
        return EmuBufferP2P(
            self._host[start:end],
            self._device,
            self._address + start * itemsize,
            owner=False,
        )

    def free(self) -> None:
        if self._owner:
            self._device.free_mem_p2p(self._address)
