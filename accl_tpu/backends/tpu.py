"""TPU backend: XLA HLO collectives over the device mesh.

Reference analog: `FPGADevice`, the hardware backend that dispatches call
descriptors to the CCLO offload engine over the 100G protocol-offload
engines (driver/xrt/src/fpgadevice.cpp).  On TPU the ICI mesh replaces
the POEs and XLA plays the CCLO's role (BASELINE.json north star): every
collective lowers to one jitted `shard_map` program whose body is the
matching XLA HLO collective (`psum`, `all_gather`, `psum_scatter`,
`all_to_all`, ...), compiled once per (scenario, shape, dtype, comm) and
cached.

Driver parity is preserved exactly: each rank holds a normal `ACCL`
handle and submits 15-word call descriptors; a world-level *gang
scheduler* (`TpuEngine`) pairs up the descriptors that the reference's
distributed firmware instances would have matched over the wire, then
runs the SPMD program for the whole gang.  One rank == one device of a
`jax.sharding.Mesh` axis named "rank"; sub-communicators map to
sub-meshes.  The same test corpus that drives the emulator drives this
backend unchanged (SURVEY §4: one suite, every rung).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from functools import lru_cache
from typing import Callable, Optional, Sequence

import numpy as np

from ..accl import ACCL
from ..arithconfig import ArithConfig
from ..buffer import BaseBuffer
from ..communicator import Communicator, Rank
from ..constants import (
    ACCLError,
    CCLOCall,
    CompressionFlags,
    ErrorCode,
    Operation,
    ReduceFunction,
    StreamFlags,
)
from ..observability import flight as _flight
from ..observability import health as _health
from ..observability import metrics as _metrics
from ..observability import trace as _trace
from ..request import Request
from ..utils.logging import get_logger
from .base import CCLODevice

# address space stride per buffer handle (addresses are opaque ids here,
# not memory offsets; slices advance within the stride)
_ADDR_STRIDE = 1 << 20


def _import_jax():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    return jax, jnp, Mesh, NamedSharding, PartitionSpec


class TpuBuffer(BaseBuffer):
    """Host numpy array paired with a single-device jax.Array resident on
    this rank's device (the FPGABuffer analog: host map + device BO)."""

    def __init__(self, host: np.ndarray, device, jax_device, address: int):
        super().__init__(host, address)
        self._device = device
        self._jax_device = jax_device
        import jax

        # copy: on the CPU rung device_put can ALIAS the host numpy
        # (zero-copy), which would let un-synced host writes leak into
        # "device" state — behavior real TPU HBM never has.  The copy
        # keeps the emulation's sync semantics faithful (same reason
        # sync_to_device copies).
        self._dev = jax.device_put(host.copy(), jax_device)

    @property
    def dev(self):
        return self._dev

    def set_dev_range(self, start: int, values) -> None:
        """Write `values` into device elements [start, start+len)."""
        if start == 0 and values.shape[0] == self._dev.shape[0] \
                and values.dtype == self._dev.dtype:
            # full overwrite: adopt the array instead of dispatching a
            # device scatter (the gang path's per-rank hot path); keep
            # the buffer pinned to its rank's device — host-built values
            # land on the default device otherwise
            import jax

            if getattr(values, "device", None) != self._jax_device:
                values = jax.device_put(values, self._jax_device)
            self._dev = values
            return
        self._dev = self._dev.at[start:start + values.shape[0]].set(values)

    def sync_to_device(self) -> None:
        import jax

        self._dev = jax.device_put(self._host.copy(), self._jax_device)

    def sync_from_device(self) -> None:
        self._host[:] = np.asarray(self._dev)

    def slice(self, start: int, end: int) -> "BaseBuffer":
        return _TpuBufferSlice(self, start, end)


class _TpuBufferSlice(BaseBuffer):
    """Sub-span view used by the driver's partial sync logic."""

    def __init__(self, parent: TpuBuffer, start: int, end: int):
        super().__init__(parent.host[start:end],
                         parent.address + start * parent.host.itemsize)
        self._parent = parent
        self._start = start
        self._end = end

    def sync_to_device(self) -> None:
        import jax
        import jax.numpy as jnp

        # copy: jnp.asarray of a host numpy slice can ALIAS it on the
        # CPU rung, and set_dev_range's full-overwrite path ADOPTS the
        # array — the same fidelity hazard TpuBuffer.__init__ copies
        # against (un-synced host writes must never leak into device
        # state)
        vals = jnp.asarray(
            np.array(self._parent.host[self._start:self._end], copy=True))
        self._parent.set_dev_range(self._start, vals)

    def sync_from_device(self) -> None:
        self._parent.host[self._start:self._end] = np.asarray(
            self._parent.dev[self._start:self._end])

    def slice(self, start: int, end: int) -> "BaseBuffer":
        return _TpuBufferSlice(self._parent, self._start + start,
                               self._start + end)


def _mark_spans(gang: dict, lane: Optional[str] = None,
                t_ready: Optional[int] = None,
                t_dispatch: Optional[int] = None,
                t_dev0: Optional[int] = None,
                t_dev1: Optional[int] = None) -> None:
    """Stamp a gang's member TraceSpans with scheduler events (no-op
    per member when tracing is off: request.trace stays None)."""
    for _call, req, _krnl in gang.values():
        span = req.trace
        if span is None:
            continue
        if lane is not None:
            span.lane = lane
        if t_ready is not None:
            span.t_gang_ready = t_ready
        if t_dispatch is not None:
            span.t_dispatch = t_dispatch
        if t_dev0 is not None:
            span.t_device_begin = t_dev0
        if t_dev1 is not None:
            span.t_device_end = t_dev1


def _mark_flight(gang: dict, state: int, lane: Optional[str] = None,
                 t: Optional[int] = None) -> None:
    """Stamp a gang's member flight records with one scheduler state
    transition — ALWAYS on (unlike _mark_spans): a handful of attribute
    writes per member, the whole per-call flight budget at this layer."""
    for _call, req, _krnl in gang.values():
        rec = req.flight
        if rec is None:
            continue
        if state == _flight.S_DISPATCHED:
            rec.mark_dispatched(lane, t)
        else:
            rec.state = state
            if state == _flight.S_GANG_READY and t is not None:
                rec.t_gang_ready = t


class PlanRing:
    """Fixed-slot submission/completion ring for one armed persistent
    plan (accl_tpu/plans.py; io_uring-style).

    Every descriptor of the captured program is pre-resolved at arm
    time into a *slot* — a pinned gang execution plan (buffers bound,
    SPMD program compiled), a pre-paired p2p move, or a local op — so
    a replay is nothing but a sequence-counter bump: the rank's
    ``gen``-th replay joins generation ``gen``; the LAST member to
    arrive executes every slot inline (it holds the whole world's
    pre-resolved state — the leader-dispatch economics applied to the
    entire program, one rendezvous per replay instead of one per call)
    while the others wait on the completion side of the ring.  No
    descriptor build, no dict lookups, no per-call allocation.

    ``invalid`` is the epoch fence: abort / membership change /
    reset_errors poisons the ring and wakes every waiter — a replay
    can raise on a fenced plan but never silently run it."""

    __slots__ = ("slots", "members", "nmembers", "comm_gens", "cv",
                 "rank_gen", "gen_count", "done_gen", "invalid",
                 "replays", "refs")

    def __init__(self, slots: list, members: frozenset,
                 comm_gens: dict):
        self.slots = slots
        self.members = members
        self.nmembers = len(members)
        #: per-rank plan handles sharing this ring (release_ring drops
        #: the pinned state only when the LAST holder dies)
        self.refs = 0
        #: comm id -> engine fence generation at arm time; any bump
        #: (abort/rebuild) makes the ring unreplayable
        self.comm_gens = comm_gens
        self.cv = threading.Condition()
        self.rank_gen: dict = {}    # rank -> replays this rank issued
        self.gen_count: dict = {}   # generation -> arrivals so far
        self.done_gen = 0           # completed replay generations
        self.invalid: Optional[str] = None
        self.replays = 0


class TpuEngine:
    """World-level gang scheduler + jitted collective executor."""

    def __init__(self, nranks: int, devices=None):
        jax, _, Mesh, _, _ = _import_jax()
        all_devices = devices if devices is not None else jax.devices()
        if len(all_devices) < nranks:
            raise ACCLError(
                f"need {nranks} devices, found {len(all_devices)} "
                f"(set XLA_FLAGS=--xla_force_host_platform_device_count=N)")
        self.nranks = nranks
        self.devices = list(all_devices[:nranks])
        self._dev_to_rank = {d: r for r, d in enumerate(self.devices)}
        self._lock = threading.Lock()
        # large-message (rendezvous-analog) path: payloads at or above
        # this many bytes route through the Pallas ring kernels
        # (ops/ring.py segmented drivers) instead of the XLA HLO
        # collective — the firmware's eager/rendezvous protocol switch
        # (fw send :589, set_max_eager_msg_size accl.cpp:1415-1423)
        import os as _os

        self.ring_threshold_bytes = int(
            _os.environ.get("ACCL_RING_THRESHOLD", str(4 << 20)))
        # flat-tree tuning-register hints (constants.TuningKey 0..5):
        # written through TpuDeviceView.set_tuning for parity with the
        # native engine's registers; the XLA collective owns the
        # schedule below the ring threshold so these are stored (and
        # observable) rather than consulted per dispatch
        self.tuning_registers: dict = {}
        # per-call completion barrier.  False (default): a collective
        # call completes at DISPATCH — jax arrays are async futures and
        # every consumer (the next collective's operand, sync_from_device
        # readbacks, np.asarray) forces the dependency chain, so results
        # are exact while rank threads overlap their next submission
        # with device execution (the reference fast path likewise posts
        # the descriptor and polls; fpgadevice.cpp:46-180).  True: the
        # executor blocks until the device finishes so get_duration is
        # the on-device perf-counter reading (fw :2280-2303) and any
        # async execution error surfaces in THIS call's retcode instead
        # of at the next consumer.
        self.profile_sync = (
            _os.environ.get("ACCL_PROFILE_SYNC", "0") == "1")
        # leader-dispatch fast path for blocking gangs (_dispatch_gang);
        # ACCL_LEADER_DISPATCH=0 forces every gang through the executor
        # (the pre-r6 path) — the A/B lane the callrate bench reports
        self.leader_dispatch = (
            _os.environ.get("ACCL_LEADER_DISPATCH", "1") != "0")
        # per-rank address -> buffer registry
        self._buffers: list[dict[int, TpuBuffer]] = [dict() for _ in range(nranks)]
        self._next_addr = [_ADDR_STRIDE] * nranks
        # communicators: comm_id -> list of global ranks (must agree across
        # ranks; first upload wins, later uploads validated)
        self._comms: dict[int, list[int]] = {}
        # arithmetic configs, deduplicated across per-rank uploads so ids
        # agree with the driver's table (ACCL.initialize upload order)
        self._arithcfgs: list = []
        self._arithcfg_ids: dict = {}
        # gang assembly: key -> deque of partial gangs
        self._gangs: dict = {}
        # aborted communicators (resilience): comm id -> error bits;
        # submits on them complete immediately, partial gangs drain fast
        self._aborted_comms: dict = {}
        # complete gangs awaiting execution, drained by ONE dedicated
        # executor thread (see _exec_loop): if the completing submitter
        # executed inline (r4 design), that rank thread could not
        # submit its own member of the NEXT gang, so no second gang
        # could ever complete behind a running dispatch and batches
        # never formed.  A dedicated executor lets all rank threads
        # keep submitting while a dispatch is in flight — the queue
        # depth behind it is what the batched dispatch fuses.
        self._ready: deque = deque()
        self._ready_cv = threading.Condition()
        self._shutdown = False
        # leader-dispatch fast path state (see _dispatch_gang): at most
        # ONE gang executes at any moment — either on the executor
        # thread (_exec_busy) or inline on the last-arriving rank's
        # thread (_inline_busy).  Both flags live under _ready_cv so the
        # idle check and the claim are atomic against each other.
        self._exec_busy = False
        self._inline_busy = False
        #: dispatch-lane counters live in a per-engine MetricsRegistry
        #: (observability: callrate bench lanes and the deterministic
        #: fast-path tests read these through the `stats` view).  Each
        #: key has a single writer context — leader_dispatches under the
        #: serialized inline lane, the rest on the executor thread.
        self.metrics = _metrics.MetricsRegistry()
        for k in ("leader_dispatches", "executor_dispatches", "batches",
                  "batched_gangs", "plan_replays", "plan_auto_captures"):
            self.metrics.inc(k, 0)
        self._log = get_logger("accl_tpu.tpu")
        # per-link wire telemetry twin (r15): (src rank, comm, peer
        # rank) -> counter dict in the LINK_STATS_FIELDS_V2 vocabulary.
        # The gang scheduler IS this backend's wire, so the twin
        # accounts the bytes its ring/tree schedules move per rank pair
        # at dispatch time and folds the gang-assembly straggler wait
        # into seek_wait_ns (the emulator's blocked-receiver analog):
        # every non-last member's wait is attributed to the LAST-
        # arriving rank's link — the peer that actually kept it waiting.
        self._links: dict = {}
        self._link_lock = threading.Lock()
        #: hang watchdog (observability/health.py), armed by
        #: start_watchdog once the world's per-rank flight recorders
        #: exist; fires with this engine's gang-assembly snapshot
        self._watchdog: Optional[_health.Watchdog] = None
        self._exec_thread = threading.Thread(
            target=self._exec_loop, name="accl-gang-exec", daemon=True)
        self._exec_thread.start()
        # gang signature -> resolved execution plan (see _gang_plan);
        # bounded LRU — fresh buffer addresses mint fresh signatures, so
        # an unbounded dict would pin one plan (and its buffers) per
        # training step on the non-resident path
        from collections import OrderedDict

        self._gang_plans: "OrderedDict" = OrderedDict()
        self._gang_plans_cap = 256
        # persistent-plan submission rings (accl_tpu/plans.py): armed
        # rings (pinned — NOT subject to the _gang_plans LRU), the arm
        # rendezvous board pairing concurrent per-rank arms into one
        # ring, and the per-comm fence generation rings snapshot at arm
        # (abort/rebuild bump it, fencing every dependent ring)
        self._plan_rings: list = []
        self._plan_board: list = []
        self._plan_cv = threading.Condition()
        self._comm_gen: dict = {}
        # kernel streams: (rank, strm_id) -> deque of np arrays
        self._streams: dict[tuple[int, int], deque] = {}
        self._stream_cv = threading.Condition()
        # krnl operand queues per rank (OP0_STREAM sources)
        self._krnl_in: list[deque] = [deque() for _ in range(nranks)]

    @property
    def stats(self) -> dict:
        """Dispatch-lane counter snapshot (kept as the pre-registry
        `stats` dict shape the bench and fast-path tests read)."""
        return self.metrics.counters()

    # ------------------------------------------------------------------
    # buffers / memory
    # ------------------------------------------------------------------
    def create_buffer(self, rank: int, length: int, dtype) -> TpuBuffer:
        host = np.zeros(length, dtype=dtype)
        with self._lock:
            addr = self._next_addr[rank]
            self._next_addr[rank] += _ADDR_STRIDE
        buf = TpuBuffer(host, self, self.devices[rank], addr)
        with self._lock:
            self._buffers[rank][addr] = buf
        return buf

    def resolve(self, rank: int, addr: int):
        """Map a descriptor address to (buffer, element offset)."""
        if addr == 0:
            return None, 0
        base = addr - (addr % _ADDR_STRIDE)
        buf = self._buffers[rank].get(base)
        if buf is None:
            return None, 0
        off_bytes = addr - base
        return buf, off_bytes // buf.host.itemsize

    # ------------------------------------------------------------------
    # communicators / meshes
    # ------------------------------------------------------------------
    def set_comm(self, comm: Communicator) -> int:
        members = [r.session for r in comm.ranks]
        with self._lock:
            if comm.id in self._comms:
                if self._comms[comm.id] != members:
                    raise ACCLError(
                        f"communicator {comm.id} re-uploaded with different "
                        f"membership")
            else:
                self._comms[comm.id] = members
        return comm.id

    def register_arithcfg(self, cfg: ArithConfig) -> int:
        with self._lock:
            if cfg in self._arithcfg_ids:
                return self._arithcfg_ids[cfg]
            self._arithcfgs.append(cfg)
            self._arithcfg_ids[cfg] = len(self._arithcfgs) - 1
            return self._arithcfg_ids[cfg]

    def wire_dtype_for(self, arithcfg_id: int) -> str:
        """Wire (compressed) representation of an arithcfg pair: "" when
        the pair is identity, else the jnp dtype name selected by the
        compressor lane (arithconfig.py COMPRESS_* ids).  The int8
        block-scaled lane (r17) is a SPEC, not a flat dtype —
        ``int8:<block>:<ef>`` — parsed by :func:`_parse_wire_spec` and
        routed through the ops/quantized.py kernels."""
        if not (0 <= arithcfg_id < len(self._arithcfgs)):
            return ""
        from ..arithconfig import COMPRESSOR_WIRE_DTYPE

        cfg = self._arithcfgs[arithcfg_id]
        if cfg.elem_ratio_log == 0:
            return ""
        from ..arithconfig import DEFAULT_COMPRESS_BLOCK

        name = COMPRESSOR_WIRE_DTYPE.get(cfg.compressor_tdest, "")
        if name == "int8":
            return (f"int8:{cfg.block or DEFAULT_COMPRESS_BLOCK}"
                    f":{int(bool(cfg.error_feedback))}")
        return name

    @lru_cache(maxsize=64)
    def _mesh_for(self, members: tuple) -> "object":
        _, _, Mesh, _, _ = _import_jax()
        devs = np.array([self.devices[m] for m in members])
        return Mesh(devs, ("rank",))

    # ------------------------------------------------------------------
    # gang scheduling
    # ------------------------------------------------------------------
    def submit(self, rank: int, call: CCLOCall, request: Request) -> None:
        scenario = call.scenario
        if scenario in (Operation.config, Operation.nop):
            request.complete(0, 0.0)
            return
        # abort fence (resilience): calls on an aborted comm finalize
        # fast instead of assembling a gang that can never complete
        if self._aborted_comms:
            err = self._aborted_comms.get(call.comm)
            if err is not None:
                request.complete(err, 0.0)
                return
        span = request.trace
        rec = request.flight
        try:
            if scenario in (Operation.copy, Operation.combine):
                if rec is not None:
                    rec.mark_dispatched("local", _trace.now_ns())
                if span is not None:
                    span.lane = "local"
                    span.t_dispatch = span.t_device_begin = _trace.now_ns()
                if scenario == Operation.copy:
                    self._exec_copy(rank, call)
                else:
                    self._exec_combine(rank, call)
                if span is not None:
                    span.t_device_end = _trace.now_ns()
                request.complete(0, 1.0)
                return
            if scenario in (Operation.send, Operation.recv):
                if rec is not None:
                    rec.mark_dispatched("p2p", _trace.now_ns())
                if span is not None:
                    span.lane = "p2p"
                    span.t_dispatch = span.t_device_begin = _trace.now_ns()
                if scenario == Operation.send:
                    self._submit_send(rank, call, request)
                else:
                    self._submit_recv(rank, call, request)
                return
            self._submit_collective(rank, call, request)
        except Exception as e:  # surface as engine error, not a hang
            from ..constants import ErrorCode

            request.description += f" [{e}]"
            request.complete(int(ErrorCode.DMA_INTERNAL_ERROR), 0.0)

    # -- local ops -----------------------------------------------------
    def _exec_copy(self, rank: int, call: CCLOCall) -> None:
        n = call.count
        # stream-flagged variants (reference copy_to_stream /
        # copy_from_stream, accl.cpp:310 + stream flag algebra): OP0
        # from the local compute-kernel queue, RES into the local
        # kernel stream keyed by the descriptor tag
        if call.stream_flags & StreamFlags.OP0_STREAM:
            q_in = self._krnl_in[rank]
            vals = q_in.popleft() if q_in else None
            if vals is None or vals.shape[0] < n:
                raise ACCLError(
                    f"stream operand {0 if vals is None else vals.shape[0]}"
                    f" elems < required {n}")
            vals = vals[:n]
        else:
            src, soff = self.resolve(rank, call.addr_0)
            vals = src.dev[soff:soff + n]
        if call.stream_flags & StreamFlags.RES_STREAM:
            self._push_stream(rank, call.tag, vals)
            return
        dst, doff = self.resolve(rank, call.addr_2)
        if vals.dtype != dst.dev.dtype:  # per-operand compression: the
            vals = vals.astype(dst.dev.dtype)  # quantize/dequantize lane
        dst.set_dev_range(doff, vals)

    def _exec_combine(self, rank: int, call: CCLOCall) -> None:
        import jax.numpy as jnp

        op0, o0 = self.resolve(rank, call.addr_0)
        op1, o1 = self.resolve(rank, call.addr_1)
        res, o2 = self.resolve(rank, call.addr_2)
        n = call.count
        a, b = op0.dev[o0:o0 + n], op1.dev[o1:o1 + n]
        # mixed-precision combine: arithmetic in the widest operand dtype,
        # result cast to the result buffer's representation (the arithcfg
        # lane selection, arithconfig.py; per-operand OP0/OP1/RES flags)
        cd = a.dtype if a.dtype.itemsize >= b.dtype.itemsize else b.dtype
        a, b = a.astype(cd), b.astype(cd)
        out = jnp.maximum(a, b) if call.function == int(
            ReduceFunction.MAX) else a + b
        res.set_dev_range(o2, out.astype(res.dev.dtype))

    # -- point-to-point ------------------------------------------------
    def _submit_send(self, rank: int, call: CCLOCall, request: Request) -> None:
        import jax

        src, soff = self.resolve(rank, call.addr_0)
        n = call.count
        if call.stream_flags & StreamFlags.OP0_STREAM:
            data = self._krnl_in[rank].popleft()[:n]
        else:
            data = src.dev[soff:soff + n]
        if call.compression_flags & CompressionFlags.ETH_COMPRESSED:
            data = _wire_roundtrip(data, self.wire_dtype_for(call.arithcfg))
        members = self._comms[call.comm]
        dst_rank = members[call.root_src_dst]
        if call.stream_flags & StreamFlags.RES_STREAM:
            # stream_put: land in the destination's kernel stream
            moved = jax.device_put(data, self.devices[dst_rank])
            self._push_stream(dst_rank, call.tag, moved)
            request.complete(0, 1.0)
            return
        # buffered eager semantics: capture payload, complete the sender,
        # deliver when the matching recv arrives.  The channel key
        # carries NO tag — tags are matched at seek time so a TAG_ANY
        # recv pairs with any pending send, the same wildcard semantics
        # the emulator's rx pool implements (native/src/rxpool.hpp,
        # reference rxbuf_seek.cpp:19-78)
        gkey = ("p2p", call.comm, rank, dst_rank)
        with self._lock:
            q = self._gangs.setdefault(gkey, deque())
            q.append(("data", call.tag, data))
        self._try_deliver(gkey)
        request.complete(0, 1.0)

    def _submit_recv(self, rank: int, call: CCLOCall, request: Request) -> None:
        members = self._comms[call.comm]
        src_rank = members[call.root_src_dst]
        gkey = ("p2p", call.comm, src_rank, rank)
        with self._lock:
            q = self._gangs.setdefault(gkey, deque())
            q.append(("recv", call.tag, (rank, call, request)))
        self._try_deliver(gkey)

    def _try_deliver(self, gkey) -> None:
        import jax
        from ..constants import ErrorCode, TAG_ANY

        while True:
            seq_err = None
            with self._lock:
                q = self._gangs.get(gkey)
                if not q:
                    return
                # seek semantics shared with the emulator rung (rxpool
                # seek, native/src/rxpool.hpp:67-78; reference
                # rxbuf_seek.cpp + dma_mover seqn check :579-611): the
                # per-src sequence counter is shared across tags, so the
                # OLDEST recv pairs with the OLDEST pending send; the
                # recv's tag must equal the send's (TAG_ANY matches
                # any), and a mismatch at the head of the stream is the
                # sequence-discipline violation PACK_SEQ_NUMBER_ERROR —
                # NOT a reorder opportunity
                datas = [i for i, e in enumerate(q) if e[0] == "data"]
                recvs = [i for i, e in enumerate(q) if e[0] == "recv"]
                if not datas or not recvs:
                    return
                ri, di = recvs[0], datas[0]
                rtag, dtag = q[ri][1], q[di][1]
                if rtag != TAG_ANY and rtag != dtag:
                    # consume the recv, leave the data queued (the emu
                    # pool keeps mismatched entries for a future
                    # wildcard/same-tag seek)
                    seq_err = q[ri][2]
                    del q[ri]
                else:
                    data = q[di][2]
                    rank, call, request = q[ri][2]
                    for i in sorted((ri, di), reverse=True):
                        del q[i]
            if seq_err is not None:
                _, _, request = seq_err
                request.complete(int(ErrorCode.PACK_SEQ_NUMBER_ERROR), 0.0)
                continue
            dst, doff = self.resolve(rank, call.addr_2)
            n = call.count
            moved = jax.device_put(data[:n], self.devices[rank])
            if call.compression_flags & CompressionFlags.ETH_COMPRESSED:
                moved = _wire_roundtrip(moved,
                                        self.wire_dtype_for(call.arithcfg))
            if dst is not None and moved.dtype != dst.dev.dtype:
                # per-operand compression: land in the RES representation
                moved = moved.astype(dst.dev.dtype)
            if call.stream_flags & StreamFlags.RES_STREAM:
                self._push_stream(rank, call.tag, moved)
            else:
                dst.set_dev_range(doff, moved)
            if request.trace is not None:  # delivery == device window end
                request.trace.t_device_end = _trace.now_ns()
            request.complete(0, 1.0)

    # -- collectives ---------------------------------------------------
    def _submit_collective(self, rank: int, call: CCLOCall,
                           request: Request) -> None:
        members = self._comms[call.comm]
        P = len(members)
        # an OP0_STREAM operand is RESERVED in the submitting rank's own
        # thread, preserving the reference's call-order stream pairing —
        # popping at gang-execution time (an arbitrary member's thread)
        # would let a later local stream op on this rank steal it
        krnl = None
        if call.stream_flags & StreamFlags.OP0_STREAM:
            in_len = call.count * (
                P if Operation(call.scenario) in (
                    Operation.scatter, Operation.reduce_scatter,
                    Operation.alltoall) else 1)
            q_in = self._krnl_in[rank]
            krnl = q_in.popleft() if q_in else None
            if krnl is None or krnl.shape[0] < in_len:
                # silent truncation/zero-padding of a short stream
                # operand would corrupt the reduction with retcode 0
                request.description += (
                    f" [stream operand {0 if krnl is None else krnl.shape[0]}"
                    f" elems < required {in_len}]")
                request.complete(
                    int(ErrorCode.SEGMENTER_EXPECTED_BTT_ERROR), 0.0)
                return
        gkey = ("coll", int(call.scenario), call.comm, call.tag)
        # link twin (r15): gang-arrival stamp for straggler-wait
        # attribution (one clock read per collective submit)
        request.link_arrival_ns = _trace.now_ns()
        ready = None
        with self._lock:
            q = self._gangs.setdefault(gkey, deque())
            # find first gang this rank hasn't joined yet (FIFO per key)
            for gang in q:
                if rank not in gang:
                    gang[rank] = (call, request, krnl)
                    if len(gang) == P:
                        ready = gang
                        q.remove(gang)
                    break
            else:
                gang = {rank: (call, request, krnl)}
                q.append(gang)
                if P == 1:
                    ready = gang
                    q.remove(gang)
        if ready is not None:
            t_ready = _trace.now_ns()  # last member arrived: gang exists
            _mark_flight(ready, _flight.S_GANG_READY, t=t_ready)
            if _trace.enabled():
                _mark_spans(ready, t_ready=t_ready)
            self._account_gang_wait(call.comm, ready, t_ready)
            # plan auto-capture (ACCL_PLAN_AUTO): arm a one-slot ring
            # when EVERY member of this instance declared intent — the
            # agreement rides the gang itself, so all ranks switch to
            # replay on the same future instance.  One attr read per
            # member on the ready path, only here.
            if all(r_.plan_intent for _c, r_, _k in ready.values()):
                self._arm_auto_ring(int(call.scenario), call.comm,
                                    ready)
            self._dispatch_gang(int(call.scenario), call.comm, ready,
                                request)

    def _dispatch_gang(self, scenario: int, comm_id: int, gang: dict,
                       leader_req: Request) -> None:
        """Route one complete gang to its dispatch lane.

        Leader-dispatch fast path (the reference's post-and-poll call
        economics, fpgadevice.cpp:24-33): when every member's request
        is BLOCKING (sync-resident), the last-arriving rank runs the
        fused program inline on its own thread — no executor wakeup on
        the way in, and the leader's own completion needs no futex wait
        on the way out, so the critical path loses one full thread
        rendezvous.  Safe because every member's submitter is parked in
        Request.wait until this very gang completes: inline execution
        cannot stall anyone's next submission (the r4 inline design
        failed exactly there for ASYNC submitters, which is why the
        async lane keeps the posted-descriptor + executor path and its
        gang batching).

        The inline run is DEFERRED to the leader's Request.wait (the
        pre_wait hook): this method is reached under the leader rank's
        RequestQueue submission lock, and executing the gang program
        there would stall a concurrent submission on the same handle
        for the whole device dispatch — wait() runs microseconds later
        on the same thread, after the lock is released.  A sync gang's
        leader waits by definition, so the thunk always runs.

        The fast path requires the engine to be otherwise IDLE — no
        queued gangs and no dispatch in flight — so execution stays
        globally one-at-a-time in gang-completion order, exactly the
        executor's serialization (concurrent dispatch of two gangs
        sharing a member's buffers would race the rebind).  Any async
        member, or a busy engine at thunk-run time, falls back to the
        executor queue."""
        if self.leader_dispatch and all(
                req.sync for _c, req, _k in gang.values()):

            def run_inline() -> None:
                with self._ready_cv:
                    idle = (not self._ready and not self._exec_busy
                            and not self._inline_busy)
                    if idle:
                        self._inline_busy = True
                if not idle:
                    self._enqueue_ready(scenario, comm_id, gang)
                    return
                try:
                    self.metrics.inc("leader_dispatches")
                    _mark_flight(gang, _flight.S_DISPATCHED,
                                 lane="leader", t=_trace.now_ns())
                    if _trace.enabled():
                        _mark_spans(gang, lane="leader")
                    self._exec_gang(scenario, comm_id, gang)
                finally:
                    with self._ready_cv:
                        self._inline_busy = False
                        if self._ready or self._shutdown:
                            self._ready_cv.notify()

            leader_req.pre_wait = run_inline
            return
        self._enqueue_ready(scenario, comm_id, gang)

    def _enqueue_ready(self, scenario: int, comm_id: int,
                       gang: dict) -> None:
        with self._ready_cv:
            self._ready.append((scenario, comm_id, gang))
            self._ready_cv.notify()

    # -- per-link wire telemetry twin (r15) ----------------------------
    def _link_add(self, src: int, comm: int, peer: int, **counts) -> None:
        with self._link_lock:
            row = self._links.setdefault((src, comm, peer), {})
            for k, v in counts.items():
                row[k] = row.get(k, 0) + int(v)

    @staticmethod
    def _wire_ratio(wire_dtype: str) -> float:
        """Wire bytes per logical byte for a wire spec ("" = 1.0): the
        cast lanes halve the payload; the int8 block-scaled lane packs
        ~4:1 plus one fp32 scale per block."""
        if not wire_dtype:
            return 1.0
        name, block, _ef = _parse_wire_spec(wire_dtype)
        if name == "int8":
            return (1.0 + 4.0 / max(block, 1)) / 4.0
        return 0.5  # float16 / bfloat16

    def _account_gang_links(self, op, comm_id: int, gang: dict,
                            nbytes: int, wire_dtype: str = "") -> None:
        """Fold one dispatched gang into the link twin.

        Ring collectives move ``busbw_factor × nbytes`` per rank to its
        right ring neighbor over P-1 (allgather/reduce_scatter) or
        2(P-1) (allreduce) hops — the same nccl-tests accounting the
        metrics registry derives bandwidth from, so the matrix and the
        busbw gauges agree by construction.  Rooted collectives
        attribute the payload to the root<->member links.  With a
        compressed ``wire_dtype`` the same logical traffic is also
        accounted at its compressed wire width (comp_tx_bytes per link,
        compressed_tx_* engine counters — the r17 bytes-saved plane)."""
        members = self._comms.get(comm_id, [])
        P = len(members)
        if P < 2 or nbytes <= 0:
            return
        name = Operation(op).name
        ratio = self._wire_ratio(wire_dtype)
        if ratio < 1.0:
            # nbytes is in_len * itemsize, which ALREADY carries the P
            # factor for the n*P-operand collectives — divide it back
            # out so logical = descriptor count x payload_factor, the
            # same convention the native engine and metrics use
            per_count = nbytes // (
                P if name in ("scatter", "reduce_scatter", "alltoall")
                else 1)
            logical = int(per_count * _metrics.payload_factor(name, P))
            self.metrics.inc("compressed_tx_logical_bytes", logical)
            self.metrics.inc("compressed_tx_bytes", int(logical * ratio))
        if name in ("allreduce", "allgather", "reduce_scatter",
                    "alltoall"):
            # nbytes is the per-rank operand (plan in_len); the busbw
            # factors apply to the TOTAL moved payload, which for
            # allgather is P x the per-rank contribution (the
            # nccl-tests payload_factor convention)
            if name == "allgather":
                nbytes *= P
            per_link = int(nbytes * _metrics.busbw_factor(name, P))
            hops = 2 * (P - 1) if name == "allreduce" else P - 1
            comp = int(per_link * ratio) if ratio < 1.0 else 0
            for i, src in enumerate(members):
                right = members[(i + 1) % P]
                left = members[(i - 1) % P]
                self._link_add(src, comm_id, right, tx_msgs=hops,
                               tx_bytes=per_link, comp_tx_bytes=comp)
                self._link_add(src, comm_id, left, rx_msgs=hops,
                               rx_bytes=per_link)
        elif name in ("bcast", "scatter", "gather", "reduce"):
            root_local = next(iter(gang.values()))[0].root_src_dst
            root = members[root_local] if root_local < P else members[0]
            to_root = name in ("gather", "reduce")
            # scatter's operand is the root's WHOLE input (in_len =
            # n*P); each root->member link carries only its 1/P slice
            per_link = nbytes // P if name == "scatter" else nbytes
            comp = int(per_link * ratio) if ratio < 1.0 else 0
            for m in members:
                if m == root:
                    continue
                a, b = (m, root) if to_root else (root, m)
                self._link_add(a, comm_id, b, tx_msgs=1,
                               tx_bytes=per_link, comp_tx_bytes=comp)
                self._link_add(b, comm_id, a, rx_msgs=1,
                               rx_bytes=per_link)

    def _account_gang_wait(self, comm_id: int, gang: dict,
                           t_ready: int) -> None:
        """Straggler wait as the seek-latency analog: every non-last
        member's (t_last − t_own) is attributed to the LAST-arriving
        rank's link — the peer that actually kept the gang waiting."""
        arrivals = {r: getattr(req, "link_arrival_ns", None)
                    for r, (_c, req, _k) in gang.items()}
        known = {r: t for r, t in arrivals.items() if t is not None}
        if len(known) < 2:
            return
        last_rank = max(known, key=lambda r: known[r])
        t_last = known[last_rank]
        for r, t in known.items():
            if r == last_rank:
                continue
            self._link_add(r, comm_id, last_rank, seeks=1,
                           seek_wait_ns=max(t_last - t, 0))

    def link_stats_for(self, rank: int) -> list:
        """One rank's link rows in the LINK_STATS_FIELDS_V2 vocabulary
        (TpuDeviceView.link_stats body).  Peers are GLOBAL ranks — the
        gang scheduler addresses members globally; on comm 0 the two
        vocabularies coincide, which is what link_matrix folds."""
        from ..observability import telemetry as _telemetry

        rows = []
        with self._link_lock:
            for (src, comm, peer), c in sorted(self._links.items()):
                if src != rank:
                    continue
                row = {"comm": comm, "peer": peer}
                for f in _telemetry.LINK_COUNTER_FIELDS:
                    row[f] = int(c.get(f, 0))
                rows.append(row)
        return rows

    def abort_comm(self, comm_id: int, err_bits: int) -> bool:
        """Epoch-analog abort for the in-process TPU engine: mark the
        comm aborted (future submits finalize immediately) and drain
        every PARTIAL gang and pending p2p recv on it, completing their
        requests with `err_bits` — blocked waiters on every rank wake
        at once.  Complete gangs already queued for dispatch run to
        completion (they have all members; executing them is safe).
        The gang-table rebuild half of elastic recovery starts here:
        the dead comm's cached execution plans are evicted so a grown
        successor never pins the old world's buffers or meshes."""
        drained = []
        with self._lock:
            self._aborted_comms[comm_id] = err_bits
            # epoch fence for persistent plans: any ring armed against
            # the pre-abort world is now stale
            self._comm_gen[comm_id] = self._comm_gen.get(comm_id, 0) + 1
            for key in list(self._gangs):
                if key[0] == "coll" and key[2] == comm_id:
                    for gang in self._gangs.pop(key):
                        drained.extend(req for _c, req, _k in gang.values())
                elif key[0] == "p2p" and key[1] == comm_id:
                    for entry in self._gangs.pop(key):
                        if entry[0] == "recv":
                            drained.append(entry[2][2])
            for sig in [s for s in self._gang_plans if s[1] == comm_id]:
                del self._gang_plans[sig]
        self.invalidate_rings(comm_id, "communicator aborted")
        for req in drained:
            if not req.done:
                req.complete(err_bits, 0.0)
        return True

    # ------------------------------------------------------------------
    # elastic membership (r11): sponsor-side state sync + rebuild
    # ------------------------------------------------------------------
    def comm_count(self) -> int:
        """Comm slots this world-level scheduler knows (the in-process
        twin of the native engine's comm_count): the join path pads a
        late rank's driver table to this before the grown upload."""
        with self._lock:
            return (max(self._comms) + 1) if self._comms else 0

    def export_join_state(self, comm_id: int = 0) -> dict:
        """Sponsor-side state sync for an in-process joiner: the
        world's comm-slot count, the abort fence table, and the
        members of the comm being recovered — everything a replacement
        rank's driver needs to align before adopting a grown comm.
        (The wire Join/Welcome/StateSync exchange of the emulator rung
        collapses to this dict: the scheduler IS the control plane.)"""
        with self._lock:
            return {
                "comm_count": (max(self._comms) + 1) if self._comms
                else 0,
                "aborted": dict(self._aborted_comms),
                "members": list(self._comms.get(comm_id, [])),
            }

    def rebuild_gang_tables(self, comm_id: int) -> int:
        """Drop every partial gang and cached plan referencing
        ``comm_id`` (grow path: a successor comm must assemble against
        a clean table — a stale partial gang from the dead world could
        otherwise swallow a new member's first call).  Returns how many
        entries were evicted; their requests finalize with the comm's
        abort bits (or COMM_ABORTED when it was never aborted)."""
        err = None
        drained = []
        with self._lock:
            err = self._aborted_comms.get(
                comm_id, int(ErrorCode.COMM_ABORTED))
            self._comm_gen[comm_id] = self._comm_gen.get(comm_id, 0) + 1
            evicted = 0
            for key in [k for k in self._gangs
                        if (k[0] == "coll" and k[2] == comm_id)
                        or (k[0] == "p2p" and k[1] == comm_id)]:
                for gang in self._gangs.pop(key):
                    evicted += 1
                    if isinstance(gang, dict):  # coll: rank -> entry
                        drained.extend(
                            req for _c, req, _k in gang.values())
                    elif gang[0] == "recv":  # p2p pending recv tuple
                        # ("recv", tag, (rank, call, request)) — same
                        # shape abort_comm finalizes: the blocked
                        # waiter must wake NOW, not at the driver
                        # budget ("data" entries carry no request)
                        drained.append(gang[2][2])
            for sig in [s for s in self._gang_plans if s[1] == comm_id]:
                del self._gang_plans[sig]
                evicted += 1
        self.invalidate_rings(comm_id, "gang tables rebuilt (grow)")
        for req in drained:
            if not req.done:
                req.complete(err, 0.0)
        return evicted

    def reset_comm_errors(self) -> None:
        """Clear abort fencing (driver reset_errors path).  Every armed
        plan ring is invalidated too: reset_errors is a world-state
        discontinuity, and a healed world must re-capture rather than
        replay pre-reset state."""
        with self._lock:
            self._aborted_comms.clear()
        self.invalidate_rings(None, "reset_errors")

    # ------------------------------------------------------------------
    # persistent-plan submission rings (accl_tpu/plans.py)
    # ------------------------------------------------------------------
    def arm_plan(self, rank: int, calls: Sequence[CCLOCall],
                 expected: frozenset, timeout_s: float) -> PlanRing:
        """Arm one rank's captured descriptor stream.  Ranks arming
        concurrently (every member of ``expected``) rendezvous on the
        arm board; the LAST arrival lowers the whole group into one
        :class:`PlanRing` — gang pairing, buffer resolution, dtype
        widening, sharding construction and AOT compilation all paid
        here, once, instead of per call."""
        with self._plan_cv:
            group = None
            for g in self._plan_board:
                # join only a group with the IDENTICAL member union:
                # every rank of one logical capture derives the same
                # union (any shared gang guarantees it), and mere
                # overlap would fuse two distinct concurrent captures
                # that happen to share ranks into one broken ring.
                # Plans whose per-rank unions differ (pure-p2p chains
                # with asymmetric routes) arm-time out decodably —
                # include a barrier/gang to give every rank the union.
                if rank not in g["arrived"] and not g["building"] \
                        and g["expected"] == set(expected):
                    group = g
                    break
            if group is None:
                group = {"arrived": {}, "expected": set(expected),
                         "ring": None, "error": None, "building": False}
                self._plan_board.append(group)
            group["arrived"][rank] = list(calls)
            complete = set(group["arrived"]) >= group["expected"]
            if complete:
                group["building"] = True
        if complete:
            ring = err = None
            try:
                ring = self._build_ring(group["arrived"],
                                        group["expected"])
            except Exception as e:  # noqa: BLE001 — every armer must
                err = e             # see the same failure, not a hang
            with self._plan_cv:
                group["ring"], group["error"] = ring, err
                if group in self._plan_board:
                    self._plan_board.remove(group)
                if ring is not None:
                    self._plan_rings.append(ring)
                self._plan_cv.notify_all()
            if err is not None:
                raise err if isinstance(err, ACCLError) else ACCLError(
                    f"plan arm failed: {err}")
            with ring.cv:
                ring.refs += 1  # this rank's plan handle
            return ring
        deadline = time.monotonic() + timeout_s
        with self._plan_cv:
            while group["ring"] is None and group["error"] is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._plan_cv.wait(remaining):
                    if group["ring"] is not None \
                            or group["error"] is not None:
                        break
                    if group["building"]:
                        # the last rank arrived and the build (AOT
                        # compile) is in flight: it ALWAYS publishes a
                        # ring or an error — poisoning now would race
                        # the builder's overwrite and strand a ring
                        # whose member count includes this rank.  Wait
                        # for the build result instead.
                        deadline = time.monotonic() + timeout_s
                        continue
                    missing = sorted(set(group["expected"])
                                     - set(group["arrived"]))
                    err = ACCLError(
                        f"plan arm timed out after {timeout_s:.0f}s "
                        f"waiting for rank(s) {missing} to capture the "
                        f"same plan — capture_plan is collective over "
                        f"every gang/p2p peer of the captured program")
                    # poison + retire the group so a late arm can never
                    # complete it against this rank's abandoned calls
                    # (fellow waiters fail consistently; retries open a
                    # FRESH group)
                    group["error"] = err
                    if group in self._plan_board:
                        self._plan_board.remove(group)
                    self._plan_cv.notify_all()
                    raise err
            if group["error"] is not None:
                e = group["error"]
                raise e if isinstance(e, ACCLError) else ACCLError(
                    f"plan arm failed: {e}")
            ring = group["ring"]
        # refs outside the board lock: release_ring takes ring.cv then
        # _plan_cv, so taking ring.cv under _plan_cv would invert
        with ring.cv:
            ring.refs += 1  # this rank's plan handle
        return ring

    def _build_ring(self, lists: dict, expected: set) -> PlanRing:
        """Lower a complete arm group into ring slots: merge the
        per-rank call streams into one serializable schedule (the gang
        pairing the runtime scheduler would have done per call, done
        once), resolving every operand and pre-compiling every SPMD
        program."""
        from ..constants import TAG_ANY

        ranks = sorted(lists)
        comm_gens: dict = {}

        def note_comm(comm_id: int) -> list:
            members = self._comms.get(comm_id)
            if members is None:
                raise ACCLError(f"plan arm: unknown communicator "
                                f"{comm_id}")
            if comm_id in self._aborted_comms:
                raise ACCLError(
                    f"plan arm: communicator {comm_id} is aborted — "
                    f"recover first, then capture",
                    int(ErrorCode.COMM_ABORTED))
            comm_gens.setdefault(comm_id, self._comm_gen.get(comm_id, 0))
            return members

        heads = {r: 0 for r in ranks}
        total = sum(len(v) for v in lists.values())
        made = 0
        slots: list = []
        pending: dict = {}  # (comm, src, dst) -> deque of sends
        while made < total:
            progressed = False
            for r in ranks:
                i = heads[r]
                if i >= len(lists[r]):
                    continue
                call = lists[r][i]
                op = Operation(call.scenario)
                if call.stream_flags:
                    raise ACCLError(
                        "plan arm: stream-operand calls are not "
                        "replayable — keep stream traffic eager")
                if op in (Operation.config, Operation.nop):
                    heads[r] += 1
                    made += 1
                    progressed = True
                elif op in (Operation.copy, Operation.combine):
                    slots.append({"kind": "local", "rank": r,
                                  "call": call})
                    heads[r] += 1
                    made += 1
                    progressed = True
                elif op == Operation.send:
                    members = note_comm(call.comm)
                    dst = members[call.root_src_dst]
                    pending.setdefault((call.comm, r, dst),
                                       deque()).append((r, call))
                    heads[r] += 1
                    made += 1
                    progressed = True
                elif op == Operation.recv:
                    members = note_comm(call.comm)
                    src = members[call.root_src_dst]
                    q = pending.get((call.comm, src, r))
                    if not q:
                        continue  # sender not reached yet
                    s_rank, s_call = q.popleft()
                    if call.tag != TAG_ANY and call.tag != s_call.tag:
                        raise ACCLError(
                            f"plan arm: recv tag {call.tag} does not "
                            f"match the oldest pending send tag "
                            f"{s_call.tag} on route {s_rank}->{r} "
                            f"(the PACK_SEQ sequence discipline)")
                    sbuf, soff = self.resolve(s_rank, s_call.addr_0)
                    dbuf, doff = self.resolve(r, call.addr_2)
                    if sbuf is None or dbuf is None:
                        raise ACCLError(
                            "plan arm: p2p operand does not resolve "
                            "to a registered device buffer")
                    eth = ((int(s_call.compression_flags)
                            | int(call.compression_flags))
                           & int(CompressionFlags.ETH_COMPRESSED))
                    slots.append({
                        "kind": "p2p", "src_rank": s_rank,
                        "dst_rank": r, "src": sbuf, "soff": soff,
                        "dst": dbuf, "doff": doff, "n": call.count,
                        "wire": (self.wire_dtype_for(s_call.arithcfg)
                                 if eth else "")})
                    heads[r] += 1
                    made += 1
                    progressed = True
                else:  # gang collective
                    members = note_comm(call.comm)
                    ready = True
                    for m in members:
                        if m not in lists:
                            raise ACCLError(
                                f"plan arm: comm {call.comm} member "
                                f"{m} never captured this plan — "
                                f"every member must capture_plan the "
                                f"same program")
                        j = heads[m]
                        if j >= len(lists[m]) or \
                                (lists[m][j].scenario, lists[m][j].comm,
                                 lists[m][j].tag) != (call.scenario,
                                                      call.comm,
                                                      call.tag):
                            ready = False
                            break
                    if not ready:
                        continue
                    gang = {m: (lists[m][heads[m]], None, None)
                            for m in members}
                    plan = (None if op == Operation.barrier
                            else self._gang_plan(op, call.comm, gang))
                    slots.append({"kind": "gang", "op": op,
                                  "comm": call.comm, "gang": gang,
                                  "plan": plan})
                    for m in members:
                        heads[m] += 1
                    made += len(members)
                    progressed = True
            if not progressed:
                raise ACCLError(
                    "plan arm: captured steps do not form a "
                    "serializable schedule (cross-rank call order "
                    "diverges, or a recv waits on a send outside the "
                    "plan) — run scripts/accl_lint.py on the program")
        leftover = sum(len(q) for q in pending.values())
        if leftover:
            raise ACCLError(
                f"plan arm: {leftover} send(s) have no matching recv "
                f"inside the plan — p2p must pair within the captured "
                f"program")
        return PlanRing(slots, frozenset(expected), comm_gens)

    def ring_replay(self, rank: int, ring: PlanRing,
                    run_async: bool = False,
                    timeout_s: float = 60.0) -> int:
        """The replay hot path: bump this rank's sequence counter; the
        generation's LAST arrival executes every pre-resolved slot
        inline, everyone else rides the completion side.  Returns the
        generation (the async ticket's token)."""
        with ring.cv:
            if ring.invalid is not None:
                raise ACCLError(
                    f"plan replay: plan invalidated ({ring.invalid})",
                    int(ErrorCode.COMM_ABORTED))
            g = ring.rank_gen.get(rank, 0) + 1
            ring.rank_gen[rank] = g
            n = ring.gen_count.get(g, 0) + 1
            last = n == ring.nmembers
            if last:
                ring.gen_count.pop(g, None)
            else:
                ring.gen_count[g] = n
        if last:
            self._ring_execute(ring, g, timeout_s)
            return g
        if run_async:
            return g
        if not self.ring_wait(ring, g, timeout_s):
            raise ACCLError(
                f"plan replay: generation {g} never completed within "
                f"{timeout_s:.0f}s (a member rank stopped replaying?)")
        return g

    def ring_wait(self, ring: PlanRing, gen: int,
                  timeout_s: float = 60.0) -> bool:
        """Completion side of the ring: block until generation ``gen``
        finished.  False on timeout; raises when the ring was fenced."""
        deadline = time.monotonic() + timeout_s
        with ring.cv:
            while ring.done_gen < gen:
                if ring.invalid is not None:
                    raise ACCLError(
                        f"plan replay: plan invalidated "
                        f"({ring.invalid})",
                        int(ErrorCode.COMM_ABORTED))
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                ring.cv.wait(remaining)
        return True

    def _ring_execute(self, ring: PlanRing, gen: int,
                      timeout_s: float) -> None:
        # generation ordering: an async pump can trigger gen g while
        # g-1 is mid-execution on another thread — executions must
        # land in order (slots rebind buffers)
        deadline = time.monotonic() + timeout_s
        with ring.cv:
            while ring.done_gen < gen - 1 and ring.invalid is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ACCLError(
                        f"plan replay: generation {gen - 1} never "
                        f"completed within {timeout_s:.0f}s")
                ring.cv.wait(remaining)
            if ring.invalid is not None:
                raise ACCLError(
                    f"plan replay: plan invalidated ({ring.invalid})",
                    int(ErrorCode.COMM_ABORTED))
        # epoch fence: the comm generations must still match the armed
        # snapshot — a replay must never run on a fenced epoch
        for comm_id, gen0 in ring.comm_gens.items():
            if self._comm_gen.get(comm_id, 0) != gen0 \
                    or comm_id in self._aborted_comms:
                self._invalidate_ring(
                    ring, f"communicator {comm_id} fenced since arm")
                raise ACCLError(
                    f"plan replay: communicator {comm_id} was fenced "
                    f"(abort/epoch bump) since the plan was armed — "
                    f"re-capture on the recovered communicator",
                    int(ErrorCode.COMM_ABORTED))
        # claim the engine's one-gang-program-at-a-time slot (the same
        # serialization invariant the leader/executor lanes uphold)
        with self._ready_cv:
            while (self._ready or self._exec_busy
                   or self._inline_busy) and not self._shutdown:
                self._ready_cv.wait(0.05)
            if self._shutdown:
                raise ACCLError(
                    "plan replay: engine shut down while waiting for "
                    "the dispatch slot")
            self._inline_busy = True
        try:
            self.metrics.inc("plan_replays")
            for slot in ring.slots:
                self._exec_slot(slot)
        except Exception as e:
            self._invalidate_ring(ring, f"replay execution failed: {e}")
            if isinstance(e, ACCLError):
                raise
            raise ACCLError(f"plan replay failed: {e}") from e
        finally:
            with self._ready_cv:
                self._inline_busy = False
                if self._ready or self._shutdown:
                    self._ready_cv.notify()
        with ring.cv:
            ring.done_gen = gen
            ring.replays += 1
            ring.cv.notify_all()

    def _exec_slot(self, slot: dict) -> None:
        kind = slot["kind"]
        if kind == "gang":
            plan = slot["plan"]
            if plan is None:  # barrier: the replay rendezvous IS it
                return
            x = self._assemble_global(plan, slot["gang"])
            # link twin (r15): replayed collectives are the dominant
            # steady-state traffic under ACCL_PLAN_AUTO — without this
            # the matrix would report near-zero for exactly the lane
            # that matters (no gang-wait here: a replay rendezvouses
            # on the ring sequence, not per-member arrival)
            self._account_gang_links(
                slot["op"], slot["comm"], slot["gang"],
                plan["in_len"] * np.dtype(plan["dtype"]).itemsize,
                wire_dtype=plan["fn_args"][6])
            y = plan["compiled"](x)
            self._scatter_back(plan, y)
        elif kind == "local":
            call = slot["call"]
            if call.scenario == Operation.copy:
                self._exec_copy(slot["rank"], call)
            else:
                self._exec_combine(slot["rank"], call)
        else:  # p2p: pre-paired direct device-to-device move
            import jax

            data = slot["src"].dev[slot["soff"]:slot["soff"]
                                   + slot["n"]]
            if slot["wire"]:
                data = _wire_roundtrip(data, slot["wire"])
            moved = jax.device_put(data, self.devices[slot["dst_rank"]])
            dst = slot["dst"]
            if moved.dtype != dst.dev.dtype:
                moved = moved.astype(dst.dev.dtype)
            dst.set_dev_range(slot["doff"], moved)

    def _invalidate_ring(self, ring: PlanRing, reason: str) -> None:
        with ring.cv:
            if ring.invalid is None:
                ring.invalid = reason
            ring.cv.notify_all()

    def invalidate_rings(self, comm_id: Optional[int],
                         reason: str) -> None:
        """Fence every armed ring touching ``comm_id`` (None = all) and
        wake their waiters — called from abort/rebuild/reset, and by
        the driver's shrink/grow plan-fencing contract."""
        with self._plan_cv:
            keep = []
            for ring in self._plan_rings:
                if comm_id is None or comm_id in ring.comm_gens:
                    self._invalidate_ring(ring, reason)
                else:
                    keep.append(ring)
            self._plan_rings = keep

    def release_ring(self, ring: PlanRing) -> None:
        """Drop one rank's handle on a ring (its plan object died or
        was closed); when the LAST holder releases, the ring is fenced
        and its pinned compiled programs/buffer bindings are dropped —
        the engine must not pin dead plans' state forever (rings are
        otherwise evicted only by a comm fence)."""
        with ring.cv:
            ring.refs -= 1
            if ring.refs > 0:
                return
        self._invalidate_ring(ring, "plan released")
        with self._plan_cv:
            if ring in self._plan_rings:
                self._plan_rings.remove(ring)
        ring.slots = []  # drop the pinned gang plans/buffers now

    def _arm_auto_ring(self, scenario: int, comm_id: int,
                       gang: dict) -> None:
        """ACCL_PLAN_AUTO: every member of this gang instance carried
        plan intent — arm a one-slot ring from the gang's descriptors
        and publish it on each member's request (the driver adopts it
        after completion, so every rank switches on the SAME instance
        and no rank ever replays against an eager peer)."""
        try:
            op = Operation(scenario)
            members = self._comms[comm_id]
            gang2 = {g: (c, None, None)
                     for g, (c, _r, _k) in gang.items()}
            plan = (None if op == Operation.barrier
                    else self._gang_plan(op, comm_id, gang2))
            ring = PlanRing(
                [{"kind": "gang", "op": op, "comm": comm_id,
                  "gang": gang2, "plan": plan}],
                frozenset(members),
                {comm_id: self._comm_gen.get(comm_id, 0)})
            with self._plan_cv:
                self._plan_rings.append(ring)
            for _c, req, _k in gang.values():
                req.plan_ring = ring
            self.metrics.inc("plan_auto_captures")
        except Exception as e:  # noqa: BLE001 — auto arming is
            # best-effort: a failure keeps the eager path, never
            # breaks the call that triggered it
            self._log.warning("plan auto-capture failed: %s", e)

    def shutdown(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        with self._ready_cv:
            self._shutdown = True
            self._ready_cv.notify()

    # ------------------------------------------------------------------
    # hang diagnosis (observability/health.py watchdog integration)
    # ------------------------------------------------------------------
    def start_watchdog(self, recorders) -> Optional["object"]:
        """Arm the per-engine hang watchdog over the world's per-rank
        flight recorders (ACCL_WATCHDOG_TIMEOUT seconds; 0 disables).
        On fire, the report embeds gang_assembly_snapshot() so the
        partial gangs inside this scheduler are named directly."""
        if self._watchdog is None:
            self._watchdog = _health.Watchdog(
                recorders, introspect=self.gang_assembly_snapshot,
                name="accl-tpu").start()
        return self._watchdog

    def gang_assembly_snapshot(self) -> list:
        """Introspection hook: every PARTIAL gang still assembling in
        _gangs — which ranks arrived with what call, which members are
        missing — the engine-level truth the watchdog report pairs with
        the per-rank flight rings."""
        now = _trace.now_ns()
        out = []
        with self._lock:
            # copy under the lock: gang dicts mutate as ranks join, and
            # p2p queues hold ("data"/"recv", tag, payload) tuples
            items = [(k, ([dict(g) for g in q] if k[0] == "coll"
                          else [(e[0], e[1]) for e in q]))
                     for k, q in self._gangs.items() if q]
        for key, gangs in items:
            if key[0] == "coll":
                _kind, scenario, comm_id, tag = key
                members = self._comms.get(comm_id, [])
                for gang in gangs:
                    arrived = sorted(gang)
                    recs = [req.flight for _c, req, _k in gang.values()
                            if req.flight is not None]
                    out.append({
                        "kind": "collective",
                        "collective": Operation(scenario).name,
                        "comm": comm_id, "tag": tag,
                        "arrived": arrived,
                        "missing": [m for m in members
                                    if m not in gang],
                        "oldest_age_us": round(max(
                            (r.age_ns(now) for r in recs), default=0)
                            / 1e3, 1),
                    })
            elif key[0] == "p2p":
                _kind, comm_id, src, dst = key
                for kind, tag in gangs:
                    out.append({
                        "kind": kind,  # pending "data" or "recv"
                        "comm": comm_id, "src": src, "dst": dst,
                        "tag": tag,
                    })
        return out

    def _exec_loop(self) -> None:
        """Dedicated gang executor (see _ready above).  Mutually
        exclusive with the leader-dispatch lane: while an inline
        dispatch is in flight the executor parks, so at most one gang
        program runs at any moment (global completion-order
        serialization — the property both lanes rely on)."""
        while True:
            with self._ready_cv:
                while True:
                    if self._ready and not self._inline_busy:
                        break
                    if self._shutdown and not self._ready:
                        return
                    self._ready_cv.wait()
                scenario, comm_id, gang = self._ready.popleft()
                self._exec_busy = True
            try:
                items = self._extend_batch(scenario, comm_id, gang)
                if items is None:
                    self.metrics.inc("executor_dispatches")
                    self._exec_gang(scenario, comm_id, gang)
                else:
                    self.metrics.inc("batches")
                    self.metrics.inc("batched_gangs", len(items))
                    self._exec_gang_batch(items)
            except Exception as e:  # pragma: no cover — belt and braces
                self._log.error("executor gang dispatch failed: %s", e)
                for call, request, _k in gang.values():
                    request.description += f" [{e}]"
                    request.complete(int(ErrorCode.DMA_INTERNAL_ERROR),
                                     0.0)
            finally:
                with self._ready_cv:
                    self._exec_busy = False
                    # wake a plan-replay leader parked on the idle
                    # claim (the ring's one-program-at-a-time slot)
                    self._ready_cv.notify_all()

    #: max gangs fused into one dispatch (the reference's effective
    #: FPGAQueue depth; also bounds compiled-variant count per fn key)
    _BATCH_CAP = 8

    def _extend_batch(self, scenario: int, comm_id: int, gang: dict):
        """Try to extend `gang` with queued compatible gangs: same
        compiled program (fn_args) and no RAW hazard (a candidate
        reading a buffer an earlier member writes must wait for the
        rebind).  Only the drainer pops, so peeking then popping is
        race-free.  Returns a list of (op, comm, gang, plan) when a
        batch of >= 2 formed, else None."""
        op = Operation(scenario)
        if op in (Operation.barrier,):
            return None
        if self.profile_sync:
            # exact perf-counter mode: every gang dispatches alone so
            # get_duration is THAT call's blocking on-device time, never
            # an averaged share of a fused batch's wall clock
            return None
        with self._ready_cv:
            if not self._ready:
                return None
        plan = self._gang_plan(op, comm_id, gang)
        if plan["fn_args"][8]:
            # ring=True: the Pallas ring kernels assign fixed
            # collective_ids per segment parity; fusing two instances
            # into one program would give data-independent rings the
            # SAME barrier/ACK semaphores, which cross-device skew can
            # alias into a double-buffer overrun on real hardware —
            # ring-path gangs always dispatch alone
            return None
        items = [(op, comm_id, gang, plan)]
        res_addrs = set(plan["res_addrs"])
        while len(items) < self._BATCH_CAP:
            with self._ready_cv:
                if not self._ready:
                    break
                nscen, ncomm, ngang = self._ready[0]
            nop = Operation(nscen)
            if nop in (Operation.barrier,):
                break
            try:
                nplan = self._gang_plan(nop, ncomm, ngang)
            except Exception:  # noqa: BLE001 — candidate stays QUEUED:
                # its own execution turn will surface the error to its
                # own requests; raising here would drop already-popped
                # gangs with their requests never completed
                break
            if (nplan["fn_args"] != plan["fn_args"]
                    or nplan["opnd_addrs"] & res_addrs):
                break
            with self._ready_cv:
                popped = self._ready.popleft()
            # only the executor pops: the head cannot have changed
            items.append((nop, ncomm, popped[2], nplan))
            res_addrs |= nplan["res_addrs"]
        return items if len(items) > 1 else None

    def _exec_gang(self, scenario: int, comm_id: int, gang: dict) -> None:
        # NB: signature is stable API for the lock-discipline test spies
        # (tests/test_tpu_backend.py wraps it positionally); the leader
        # lane pre-tags its spans, everything else defaults to executor
        try:
            _mark_flight(gang, _flight.S_DISPATCHED, lane="executor",
                         t=_trace.now_ns())
            if _trace.enabled():
                td = _trace.now_ns()
                for _c, req, _k in gang.values():
                    span = req.trace
                    if span is not None:
                        if span.lane is None:
                            span.lane = "executor"
                        span.t_dispatch = td
            dt_ns, t0, t1 = self._run_collective(Operation(scenario),
                                                 comm_id, gang)
            if _trace.enabled():
                _mark_spans(gang, t_dev0=t0, t_dev1=t1)
            for call, request, _krnl in gang.values():
                request.complete(0, float(dt_ns))
        except Exception as e:
            for call, request, _krnl in gang.values():
                request.description += f" [{e}]"
                request.complete(int(ErrorCode.DMA_INTERNAL_ERROR), 0.0)

    def _exec_gang_batch(self, items) -> None:
        """K same-program, RAW-independent gangs in ONE dispatch: the
        batched compiled fn takes K sharded globals and returns K
        results (inputs are all read before any rebind, which is
        exactly the sequential semantics the RAW guard preserves)."""
        import time

        try:
            tf = _trace.now_ns()
            for _op, _c, gang, _plan in items:
                _mark_flight(gang, _flight.S_DISPATCHED, lane="batched",
                             t=tf)
            if _trace.enabled():
                td = _trace.now_ns()
                for _op, _c, gang, _plan in items:
                    _mark_spans(gang, lane="batched", t_dispatch=td)
            xs = [self._assemble_global(plan, gang)
                  for _op, _c, gang, plan in items]
            for op_, c_, gang_, plan_ in items:
                self._account_gang_links(
                    op_, c_, gang_,
                    plan_["in_len"] * np.dtype(plan_["dtype"]).itemsize,
                    wire_dtype=plan_["fn_args"][6])
            fnb = _collective_fn(*items[0][3]["fn_args"],
                                 nbatch=len(items))
            t0 = time.perf_counter_ns()
            ys = fnb(*xs)
            if self.profile_sync:
                import jax

                jax.block_until_ready(ys)
            t1 = time.perf_counter_ns()
            dt_ns = t1 - t0
            if _trace.enabled():
                # one fused device window shared by every batched gang —
                # the aligned cross-gang slice the timeline shows
                for _op, _c, gang, _plan in items:
                    _mark_spans(gang, t_dev0=t0, t_dev1=t1)
            # per-call perf counter: the batch's wall time is shared by
            # K fused dispatches, so each call's duration is its share
            # (reporting the whole batch per call would inflate
            # get_duration by the batch width)
            per_call = float(dt_ns) / len(items)
            for (op, _c, gang, plan), y in zip(items, ys):
                self._scatter_back(plan, y)
                for call, request, _krnl in gang.values():
                    request.complete(0, per_call)
        except Exception as e:
            for _op, _c, gang, _plan in items:
                for call, request, _krnl in gang.values():
                    if request.done:
                        # earlier batch members that already completed
                        # successfully must NOT be re-completed as
                        # errors (waiters may have observed success;
                        # on_complete must not run twice)
                        continue
                    request.description += f" [{e}]"
                    request.complete(int(ErrorCode.DMA_INTERNAL_ERROR),
                                     0.0)

    def _gang_plan(self, op: Operation, comm_id: int, gang: dict):
        """Resolve one gang signature into an execution plan and cache
        it: training loops repeat identical descriptors at call rate, so
        buffer resolution, dtype widening, sharding construction and the
        AOT-compile lookup are paid once per signature instead of per
        call (the hostctrl MMIO fast-path role: per-call work collapses
        to a handful of register writes, fpgadevice.cpp:46-180).
        Safe to cache: the address->buffer registry only grows, buffer
        dev dtype/shape never change, and the compiled fn is keyed on
        everything that shapes the program."""
        jax, jnp, Mesh, NamedSharding, P = _import_jax()
        members = self._comms[comm_id]
        # ring_threshold_bytes is a runtime knob (tests force the ring
        # path by setting it to 0): it shapes the compiled program, so
        # it must be part of the signature or a threshold change would
        # silently keep serving the previously-compiled lowering
        sig = (int(op), comm_id, self.ring_threshold_bytes, tuple(
            (g, c.addr_0, c.addr_2, c.count, c.root_src_dst, c.function,
             c.compression_flags, c.arithcfg, c.stream_flags, c.tag,
             c.fused)
            for g, c in ((m, gang[m][0]) for m in members)))
        # _gang_plan runs only on the dispatching context — the
        # executor thread or (leader-dispatch lane) the one inline
        # leader, never both at once — so the lock is effectively
        # uncontended here and the hit path keeps proper LRU recency
        # (an early r5 build skipped move_to_end to dodge submit-thread
        # convoying that no longer exists; past 256 live signatures
        # that cost re-compiles)
        with self._lock:
            plan = self._gang_plans.get(sig)
            if plan is not None:
                self._gang_plans.move_to_end(sig)
                return plan

        nranks = len(members)
        mesh = self._mesh_for(tuple(members))
        any_call = next(iter(gang.values()))[0]
        n = any_call.count
        root = any_call.root_src_dst
        func = any_call.function
        wire_dtype = (self.wire_dtype_for(any_call.arithcfg)
                      if any_call.compression_flags
                      & CompressionFlags.ETH_COMPRESSED else "")

        # operand length per rank in the global array
        in_len = {
            Operation.bcast: n,
            Operation.scatter: n * nranks,
            Operation.gather: n,
            Operation.allgather: n,
            Operation.reduce: n,
            Operation.allreduce: n,
            Operation.reduce_scatter: n * nranks,
            Operation.alltoall: n * nranks,
        }[op]

        # per-operand compression: run the collective in the widest
        # (uncompressed) representation present in the gang; narrower
        # operand shards are dequantized on the way in and results are
        # quantized back to each rank's result-buffer dtype on the way
        # out (the hp_compression lane role, driven by buffer dtypes the
        # same way ACCL._build derives OP0/RES_COMPRESSED)
        dtype = None
        for g in members:
            call = gang[g][0]
            for addr in (call.addr_0, call.addr_2):
                b, _o = self.resolve(g, addr)
                if b is not None and (dtype is None
                                      or b.host.dtype.itemsize
                                      > np.dtype(dtype).itemsize):
                    dtype = b.host.dtype
        if dtype is None:
            # stream->stream collectives address no buffer at all: the
            # dtype comes from the reserved kernel operands (np.dtype(
            # None) would silently mean float64 and corrupt f32 streams)
            for g in members:
                krnl = gang[g][2]
                if krnl is not None:
                    dtype = np.dtype(krnl.dtype)
                    break
        if dtype is None:
            raise ACCLError(
                "collective addresses no buffer and no stream operand "
                "was reserved — cannot derive the datapath dtype")

        ops = []
        for li, g in enumerate(members):
            call = gang[g][0]
            op0_stream = bool(call.stream_flags & StreamFlags.OP0_STREAM)
            res_stream = bool(call.stream_flags & StreamFlags.RES_STREAM)
            # operand: op0 for contributors; bcast non-root contributes its
            # result buffer as placeholder (engine ignores the content);
            # OP0_STREAM members contribute from their kernel queue at
            # call time (the mem<->stream reduce variants, test.cpp
            # :813-910)
            if op0_stream:
                buf, off, fast = None, 0, False
            else:
                buf, off = self.resolve(g, call.addr_0)
                if buf is None:
                    buf, off = self.resolve(g, call.addr_2)
                fast = (buf is not None and off == 0
                        and buf.dev.shape[0] == in_len
                        and buf.dev.dtype == dtype)
            write_out = not (op in (Operation.reduce, Operation.gather)
                             and li != root)
            res, roff = self.resolve(g, call.addr_2)
            res_tag = call.tag if (res_stream and write_out) else None
            ops.append((g, buf, off, fast,
                        res if (write_out and not res_stream) else None,
                        roff, op0_stream, res_tag))

        # large payloads ride the Pallas ring kernels (rendezvous path)
        ring = (op in (Operation.allreduce, Operation.allgather,
                       Operation.reduce_scatter)
                and nranks > 1
                and in_len * np.dtype(dtype).itemsize
                >= self.ring_threshold_bytes)

        # r18 fused lane (descriptor opt-in): the chunked pipelined ring
        # that overlaps chunk k+1's wire hop with chunk k's fold; takes
        # precedence over the threshold-selected ring/HLO paths
        fused = (bool(any_call.fused)
                 and op in (Operation.allreduce, Operation.allgather,
                            Operation.reduce_scatter)
                 and nranks > 1)

        # compiled once per (mesh, op, shape, root, func, ...) and
        # cached (no donation — see _collective_fn)
        fn_args = (mesh, op, nranks, in_len, root, func, wire_dtype,
                   str(np.dtype(dtype)), ring, fused)
        compiled = (None if op == Operation.barrier
                    else _collective_fn(*fn_args))
        plan = {
            "members": members,
            "nranks": nranks,
            "in_len": in_len,
            "dtype": dtype,
            "sharding": NamedSharding(mesh, P("rank")),
            "compiled": compiled,
            "ops": ops,
            # batching metadata: gangs with the same fn_args can share
            # one dispatch; the address sets drive the RAW guard (a
            # candidate whose operands intersect an earlier batch
            # member's results must see the rebound value, so it ends
            # the batch).  Keyed by (rank, address): the per-rank
            # allocators are symmetric — every rank mints the same
            # numeric addresses — so a raw-address set would falsely
            # alias unrelated cross-rank buffers and end batches that
            # have no hazard at all (e.g. disjoint sub-communicator
            # gangs); only a same-rank overlap is a real RAW.
            "fn_args": fn_args,
            "opnd_addrs": frozenset(
                (g, b.address) for g, b, _o, _f, _r, _ro, _os, _rt in ops
                if b is not None),
            "res_addrs": frozenset(
                (g, r.address) for g, _b, _o, _f, r, _ro, _os, _rt in ops
                if r is not None),
        }
        with self._lock:
            self._gang_plans[sig] = plan
            self._gang_plans.move_to_end(sig)
            while len(self._gang_plans) > self._gang_plans_cap:
                self._gang_plans.popitem(last=False)
        return plan

    def _run_collective(self, op: Operation, comm_id: int,
                        gang: dict) -> tuple:
        """Assemble the gang's operands into one sharded array, execute
        the AOT-compiled SPMD collective, and scatter result shards back
        into the per-rank device buffers — everything stays jax.Arrays
        on device end to end (the reference's zero-copy device-resident
        call path, accl.cpp:796-839).  The duration is execution
        nanoseconds (dispatch + device time, compile excluded — the perf-counter
        role, fw :2280-2303).

        Hot path: the plan cache resolves everything per SIGNATURE, the
        global array is 1-D with each member's whole buffer as its
        shard, and full-length results rebind buffers — a repeated call
        costs one make_array + one compiled dispatch, no per-member jax
        ops.

        Returns (duration_ns, device_begin_ns, device_end_ns) so the
        dispatch lanes can stamp the device window on member spans."""
        import time

        jax, jnp, Mesh, NamedSharding, P = _import_jax()

        if op == Operation.barrier:
            t = time.perf_counter_ns()
            return 0, t, t  # gang completion IS the synchronization

        plan = self._gang_plan(op, comm_id, gang)
        x = self._assemble_global(plan, gang)
        self._account_gang_links(
            op, comm_id, gang,
            plan["in_len"] * np.dtype(plan["dtype"]).itemsize,
            wire_dtype=plan["fn_args"][6])

        t0 = time.perf_counter_ns()
        y = plan["compiled"](x)
        if self.profile_sync:
            # exact perf-counter mode: duration is on-device time and
            # async errors surface here (see __init__)
            jax.block_until_ready(y)
        t1 = time.perf_counter_ns()

        self._scatter_back(plan, y)
        return t1 - t0, t0, t1

    def _assemble_global(self, plan: dict, gang: dict):
        jax, jnp, Mesh, NamedSharding, P = _import_jax()
        in_len = plan["in_len"]
        dtype = plan["dtype"]

        shards = []
        for g, buf, off, fast, _res, _roff, op0_stream, _rtag in plan["ops"]:
            if fast:
                # whole-buffer operand already resident on its device:
                # the buffer IS the shard (zero-copy call path,
                # accl.cpp:796-839)
                shards.append(buf.dev)
                continue
            if op0_stream:
                # the operand was RESERVED at submit time in the
                # member's own thread (call-order stream pairing)
                shard = jnp.asarray(gang[g][2])[:in_len]
            else:
                shard = buf.dev[off:off + in_len]
            if shard.dtype != dtype:
                shard = shard.astype(dtype)
            if shard.shape[0] < in_len:  # placeholder short buffer (bcast)
                pad = jnp.zeros((in_len - shard.shape[0],), shard.dtype)
                shard = jnp.concatenate([shard, pad])
            shards.append(jax.device_put(shard, self.devices[g]))

        # assembled-global cache: when every shard is the IDENTICAL
        # array object as the previous call (the steady state of a
        # training loop — all-fast-path operands, none rebound since),
        # the previous global is still an exact alias of them, so the
        # per-call make_array disappears.  Sound because jax arrays are
        # immutable: any buffer update rebinds to a NEW object and
        # misses this check.  The cache holds strong refs, so object
        # identity cannot be recycled out from under it.
        cached = plan.get("assembled")
        if (cached is not None and len(cached[0]) == len(shards)
                and all(a is b for a, b in zip(cached[0], shards))):
            return cached[1]
        x = jax.make_array_from_single_device_arrays(
            (plan["nranks"] * in_len,), plan["sharding"], shards)
        # only all-fast-path gangs can ever hit (slow-path members
        # create fresh arrays per call), so storing anything else
        # would just pin dead device copies between calls
        if all(o[3] for o in plan["ops"]):
            plan["assembled"] = (shards, x)
        return x

    def _scatter_back(self, plan: dict, y) -> None:
        # scatter result shards back into per-rank result buffers without
        # leaving the device: each addressable shard is already a
        # single-device jax.Array on its gang member's chip.  The shard
        # order for a given sharding is stable across calls, so it is
        # resolved once per plan and later calls zip straight through
        # (the dict build + Device hashing was a measured slice of the
        # per-call budget at call rate).
        shard_list = y.addressable_shards
        order = plan.get("shard_order")
        if order is None:
            order = tuple(self._dev_to_rank[s.device] for s in shard_list)
            plan["shard_order"] = order
        out_shards = dict(zip(order, (s.data for s in shard_list)))
        for g, _buf, _off, _fast, res, roff, _op0s, res_tag in plan["ops"]:
            if res_tag is not None:
                # RES_STREAM: the member's result lands in its local
                # kernel stream (uncompressed representation)
                self._push_stream(g, res_tag, out_shards[g])
                continue
            if res is None:
                continue
            out = out_shards[g]
            if (roff == 0 and out.shape[0] == res.dev.shape[0]
                    and out.dtype == res.dev.dtype):
                # whole-buffer result already on the right device: adopt
                # directly (the set_dev_range fast path minus its
                # per-call device probe — a result shard lives on its
                # member's device by construction)
                res._dev = out
                continue
            if out.dtype != res.dev.dtype:  # quantize to RES representation
                out = out.astype(res.dev.dtype)
            res.set_dev_range(roff, out)

    # ------------------------------------------------------------------
    # kernel streams
    # ------------------------------------------------------------------
    def push_krnl(self, rank: int, data: np.ndarray) -> None:
        import jax

        self._krnl_in[rank].append(
            jax.device_put(np.ascontiguousarray(data), self.devices[rank]))

    def _push_stream(self, rank: int, strm: int, data) -> None:
        """Deliver `data` into (rank, strm)'s kernel stream and wake
        waiters — the single delivery point for every RES_STREAM path
        (local copy, stream_put, recv landing, gang results)."""
        key = (rank, strm)
        with self._stream_cv:
            self._streams.setdefault(key, deque()).append(data)
            self._stream_cv.notify_all()

    def pop_stream(self, rank: int, strm: int, timeout_s: float):
        key = (rank, strm)
        with self._stream_cv:
            ok = self._stream_cv.wait_for(
                lambda: self._streams.get(key), timeout=timeout_s)
            if not ok:
                return None
            return np.asarray(self._streams[key].popleft())


def _parse_wire_spec(wire_dtype: str):
    """Decode a wire_dtype_for() spec: ("float16"|"bfloat16"|"", 0,
    False) for the cast lanes, ("int8", block, error_feedback) for the
    block-scaled lane."""
    if wire_dtype.startswith("int8"):
        from ..arithconfig import DEFAULT_COMPRESS_BLOCK

        parts = wire_dtype.split(":")
        block = int(parts[1]) if len(parts) > 1 else \
            DEFAULT_COMPRESS_BLOCK
        ef = len(parts) > 2 and parts[2] == "1"
        return "int8", block, ef
    return wire_dtype, 0, False


def _wire_roundtrip(x, wire_dtype: str):
    """Model one wire hop of compression: the payload crosses the link
    in the arithcfg's compressed representation and is decompressed on
    arrival — a dtype cast pair for the f16/bf16 lanes, a blockwise
    quantize/dequantize (ops/quantized.py) for the int8 block-scaled
    lane.  Idempotent: the absmax element of every quantized block maps
    to exactly ±127, so re-quantizing an already-roundtripped payload
    reproduces it bit-for-bit."""
    import jax.numpy as jnp

    if not wire_dtype:
        return x
    name, block, _ef = _parse_wire_spec(wire_dtype)
    if name == "int8":
        from ..ops.quantized import dequantize_blockwise, quantize_blockwise

        if x.dtype.itemsize <= 1:
            return x
        flat = x.reshape(-1).astype(jnp.float32)
        q, sc, n = quantize_blockwise(flat, block)
        return dequantize_blockwise(q, sc, n).reshape(x.shape).astype(x.dtype)
    wd = jnp.dtype(name)
    if x.dtype.itemsize > wd.itemsize:
        return x.astype(wd).astype(x.dtype)
    return x


def _tree_bcast(v, nranks: int, root: int):
    """Binomial-tree broadcast over ppermute: log2(P) rounds of doubling
    senders; every device receives the payload exactly once, so wire
    traffic is n*(P-1) total — vs n*(P-1) *per device* for the old
    all_gather-then-index lowering (the reference's rendezvous tree
    bcast, fw :816-869)."""
    import jax
    import jax.numpy as jnp

    idx = jax.lax.axis_index("rank")
    rel = (idx - root) % nranks
    k = 1
    while k < nranks:
        perm = [((root + j) % nranks, (root + j + k) % nranks)
                for j in range(k) if j + k < nranks]
        recvd = jax.lax.ppermute(v, "rank", perm)
        got_now = jnp.logical_and(rel >= k, rel < 2 * k)
        v = jnp.where(got_now, recvd, v)
        k *= 2
    return v


def _tree_gather(v, nranks: int, root: int):
    """Binomial-tree gather: payload sizes double each round
    (dynamic_slice/update at rel-rank offsets), so total wire traffic is
    O(P*n*log2(P)/2) and each non-root device forwards at most once —
    vs every device receiving the full (P-1)*n under all_gather.  The
    rel-ordered accumulator is rolled into global rank order at the end
    (the reference's ring-relay gather with stride bookkeeping,
    fw :1207-1295, re-shaped as a tree for ICI)."""
    import jax
    import jax.numpy as jnp

    n = v.shape[0]
    idx = jax.lax.axis_index("rank")
    rel = (idx - root) % nranks
    # accumulator padded to the next power of two so the doubling-block
    # dynamic slices never clamp at the edge for non-power-of-2 worlds
    # (clamping would silently shift a block over a neighbor's slice)
    pow2 = 1
    while pow2 < nranks:
        pow2 *= 2
    acc = jnp.zeros((pow2 * n,), v.dtype)
    acc = jax.lax.dynamic_update_slice(acc, v, (rel * n,))
    k = 1
    while k < nranks:
        # senders: rel % 2k == k; receivers: rel % 2k == 0 with rel+k < P
        perm = [((root + j + k) % nranks, (root + j) % nranks)
                for j in range(0, nranks, 2 * k) if j + k < nranks]
        # every device extracts its own k*n block (senders' payload)
        chunk = jax.lax.dynamic_slice(acc, (rel * n,), (k * n,))
        recvd = jax.lax.ppermute(chunk, "rank", perm)
        is_recv = jnp.logical_and(rel % (2 * k) == 0, rel + k < nranks)
        merged = jax.lax.dynamic_update_slice(acc, recvd, ((rel + k) * n,))
        acc = jnp.where(is_recv, merged, acc)
        k *= 2
    # acc holds rel-ordered slices; global rank j sits at rel (j-root)%P,
    # one static roll restores global order
    return jnp.roll(acc[:nranks * n], root * n)


@lru_cache(maxsize=256)
def _collective_fn(mesh, op: Operation, nranks: int, in_len: int, root: int,
                   func: int, wire_dtype: str, dtype: str,
                   ring: bool = False, fused: bool = False,
                   nbatch: int = 1) -> Callable:
    """Build + AOT-compile the SPMD program for one collective: a
    shard_map whose inner program is the XLA HLO collective (or the
    ppermute tree schedule) over ICI — or, with ``ring=True``, the
    segmented Pallas ring kernel (the rendezvous large-message path).
    Compilation happens here, once per cache key, so execution timing in
    the caller never includes compile (get_duration = the perf-counter
    role)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..utils.compat import shard_map

    n = in_len if op not in (Operation.scatter, Operation.reduce_scatter,
                             Operation.alltoall) else in_len // nranks
    is_max = func == int(ReduceFunction.MAX)
    # Pallas kernels execute under the TPU interpreter on the CPU rung
    interpret = jax.default_backend() == "cpu"
    red = "max" if is_max else "sum"

    def quant(v):
        # wire hop in the arithcfg's compressed representation.  NB: the
        # interior accumulate stays in the UNCOMPRESSED domain on TPU —
        # the MXU/VPU reduce natively in f32, so quantizing only at the
        # wire endpoints is both faster and strictly more accurate than
        # the emulator's reference-faithful compressed-domain lanes
        # (arith_is_compressed, arithconfig.hpp:106-119); both are within
        # the corpus's FLOAT16 tolerances (test_compression_matrix.py).
        return _wire_roundtrip(v, wire_dtype)

    def ring_body(v):
        from ..ops import ring as ring_ops

        if op == Operation.allreduce:
            return ring_ops.ring_all_reduce_segmented(
                v, "rank", op=red, interpret=interpret)
        if op == Operation.allgather:
            return ring_ops.ring_all_gather_segmented(
                v, "rank", interpret=interpret)
        return ring_ops.ring_reduce_scatter_segmented(
            v, "rank", op=red, interpret=interpret)

    # r17 quantized ring lane: with the int8 block-scaled wire spec the
    # ppermute payload IS the packed (int8, scale) block stream
    # (ops/quantized.py), with optional EQuARX error feedback carried
    # hop to hop — not a roundtrip model around a lossless ring.  SUM
    # only (the EQuARX algebra); MAX and ragged chunkings fall back to
    # the wire-roundtrip model around the plain ring below.
    wire_name, wire_block, wire_ef = _parse_wire_spec(wire_dtype)

    def q_ring_body(v):
        from ..ops import quantized as q_ops

        if op == Operation.allreduce:
            return q_ops.quantized_all_reduce(
                v, "rank", block=wire_block,
                error_feedback=wire_ef).astype(v.dtype)
        if op == Operation.allgather:
            return q_ops.quantized_ring_all_gather(
                v, "rank", block=wire_block).astype(v.dtype)
        return q_ops.quantized_ring_reduce_scatter(
            v, "rank", block=wire_block,
            error_feedback=wire_ef).astype(v.dtype)

    q_ring = (ring and wire_name == "int8" and not is_max
              and op in (Operation.allreduce, Operation.allgather,
                         Operation.reduce_scatter)
              and in_len % nranks == 0)

    # r18 fused lane twin of q_ring: the int8 quantize/dequantize runs
    # INSIDE the chunked pipeline loop (no whole-buffer pack/unpack)
    fused_q = fused and wire_name == "int8" and not is_max

    def fused_body(v):
        from ..ops import fused as fused_ops

        if fused_q:
            w = (wire_block, wire_ef)
            if op == Operation.allreduce:
                return fused_ops.chunked_ring_all_reduce(
                    v.astype(jnp.float32), "rank",
                    wire=w).astype(v.dtype)
            if op == Operation.allgather:
                return fused_ops.chunked_ring_all_gather(
                    v.astype(jnp.float32), "rank",
                    wire=w).astype(v.dtype)
            return fused_ops.chunked_ring_reduce_scatter(
                v.astype(jnp.float32), "rank", wire=w).astype(v.dtype)
        v = quant(v)
        if op == Operation.allreduce:
            out = fused_ops.chunked_ring_all_reduce(v, "rank", op=red)
        elif op == Operation.allgather:
            out = fused_ops.chunked_ring_all_gather(v, "rank")
        else:
            out = fused_ops.chunked_ring_reduce_scatter(v, "rank", op=red)
        return quant(out)

    def body(v):  # v: [in_len] block on each device (1-D global layout:
        # the per-rank shard IS the member's buffer, no reshape on the
        # way in or out — the gang hot path stays dispatch-free)
        if fused:
            # the fused lane owns its wire hops end to end (int8 inside
            # the loop body; cast lanes roundtrip at the endpoints)
            return fused_body(v)
        if q_ring:
            # the quantized kernels own the wire hops end to end — no
            # extra entry/exit roundtrip (that would double-quantize)
            return q_ring_body(v.astype(jnp.float32)).astype(v.dtype)
        v = quant(v)
        if ring:
            out = ring_body(v)
        elif op == Operation.allreduce or op == Operation.reduce:
            out = (jax.lax.pmax(v, "rank") if is_max
                   else jax.lax.psum(v, "rank"))
        elif op == Operation.bcast:
            out = _tree_bcast(v, nranks, root)
        elif op == Operation.gather:
            out = _tree_gather(v, nranks, root)
        elif op == Operation.allgather:
            out = jax.lax.all_gather(v, "rank").reshape(-1)
        elif op == Operation.scatter:
            # only the root's operand matters: mask everyone else to
            # zero and ride the bandwidth-optimal reduce-scatter ring —
            # O(n*P) total wire traffic vs O(n*P^2) for all_gather
            idx = jax.lax.axis_index("rank")
            masked = jnp.where(idx == root, v, jnp.zeros_like(v))
            out = jax.lax.psum_scatter(masked, "rank", scatter_dimension=0,
                                       tiled=True)
        elif op == Operation.reduce_scatter:
            if is_max:
                # XLA has no pmax_scatter: reduce fully, keep own chunk
                # (correct first; MAX reduce_scatter is a cold lane —
                # the SUM path keeps the bandwidth-optimal ring)
                idx = jax.lax.axis_index("rank")
                out = jax.lax.dynamic_slice_in_dim(
                    jax.lax.pmax(v, "rank"), idx * n, n)
            else:
                out = jax.lax.psum_scatter(v, "rank",
                                           scatter_dimension=0,
                                           tiled=True)
        elif op == Operation.alltoall:
            blocks = v.reshape(nranks, n)
            out = jax.lax.all_to_all(blocks, "rank", split_axis=0,
                                     concat_axis=0, tiled=False)
            out = out.reshape(-1)
        else:
            raise ACCLError(f"collective {op} not lowered")
        return quant(out)

    # vma checking can't see through the Pallas remote-DMA kernels
    fn = shard_map(body, mesh=mesh, in_specs=P("rank"),
                   out_specs=P("rank"), check_vma=not ring)
    arg = jax.ShapeDtypeStruct(
        (nranks * in_len,), np.dtype(dtype),
        sharding=NamedSharding(mesh, P("rank")))
    # NO donation: the per-rank shards ARE the registered device buffers
    # on the fast path (the member may reuse its send buffer on the very
    # next call), so the input must stay alive across the dispatch
    if nbatch == 1:
        return jax.jit(fn).lower(arg).compile()
    # batched gang dispatch (the reference's queue-depth amortization,
    # FPGAQueue acclrequest.hpp:153-211): K independent same-shape
    # gangs ride ONE compiled program — K inputs, K outputs, no
    # concatenation — so the per-dispatch overhead is paid once per
    # batch instead of once per call
    def batched(*vs):
        return tuple(fn(v) for v in vs)

    return jax.jit(batched).lower(*([arg] * nbatch)).compile()


class TpuDeviceView(CCLODevice):
    """One rank's CCLO handle over the shared TpuEngine (the per-rank
    driver-facing face of the world-level backend)."""

    #: all ranks share one TpuEngine comm table keyed by comm id, so a
    #: disjoint sub-group must get a DISTINCT id world-wide; the
    #: hierarchical composer (accl_tpu/tuning/compose.py) reads this to
    #: decide whether a non-member rank pads its id space driver-side
    #: only (shared table: the members' upload covers the world) or
    #: must upload an inert pad comm (per-rank engine tables: emu)
    comm_table_is_shared = True

    def __init__(self, engine: TpuEngine, rank: int):
        self._engine = engine
        self._rank = rank
        self._mem = {}

    def start(self, call: CCLOCall, request: Request) -> None:
        self._engine.submit(self._rank, call, request)

    def sanitizer_domain(self):
        """All ranks of a TpuWorld share one in-process TpuEngine, so
        the engine's identity is the sanitizer exchange domain: a
        mismatched gang raises at submit instead of assembling two
        forever-partial gangs in the scheduler."""
        return ("tpu", id(self._engine))

    @property
    def engine_metrics(self) -> "object":
        """The shared engine's registry (ACCL.metrics() merges its
        dispatch-lane counters under engine/ keys)."""
        return self._engine.metrics

    def engine_stats(self) -> dict:
        """Engine telemetry snapshot (r14) in the same flat schema as
        the native engine's ``accl_engine_stats`` where the concepts
        map (plans/replays), plus the TPU-only dispatch-lane and
        plan-ring fields (generation = max comm fence generation,
        refcounts = per-rank handles pinning live rings).  The
        world-level sampler polls this exactly like the emu twin."""
        eng = self._engine
        counters = eng.metrics.counters()
        with eng._plan_cv:
            rings = [r for r in eng._plan_rings if r.invalid is None]
            plans_live = len(rings)
            plan_ring_refs = sum(r.refs for r in rings)
            ring_replays = sum(r.replays for r in rings)
        with eng._ready_cv:
            ready_depth = len(eng._ready)
        with eng._lock:  # _comm_gen mutates under _lock (abort/evict)
            gen = max(eng._comm_gen.values(), default=0)
        with eng._link_lock:
            link_rows = sum(1 for (src, _c, _p) in eng._links
                            if src == self._rank)
        return {
            "version": 3,
            "link_rows": link_rows,
            "compressed_tx_bytes":
                counters.get("compressed_tx_bytes", 0),
            "compressed_tx_logical_bytes":
                counters.get("compressed_tx_logical_bytes", 0),
            "plans_live": plans_live,
            "plan_ring_refs": plan_ring_refs,
            "plan_ring_generation": gen,
            "plan_ring_replays": ring_replays,
            "plan_replays": counters.get("plan_replays", 0),
            "plan_auto_captures": counters.get("plan_auto_captures", 0),
            "leader_dispatches": counters.get("leader_dispatches", 0),
            "executor_dispatches": counters.get("executor_dispatches", 0),
            "batches": counters.get("batches", 0),
            "batched_gangs": counters.get("batched_gangs", 0),
            "ready_depth": ready_depth,
        }

    def link_stats(self) -> list:
        """Per-(comm, peer) wire-counter rows (r15) — the TPU twin of
        EmuDevice.link_stats: ring/tree schedule bytes accounted at
        gang dispatch, gang-assembly straggler wait as seek_wait_ns.
        Peers are global ranks (== comm-local on comm 0)."""
        return self._engine.link_stats_for(self._rank)

    # memory API kept for interface completeness; TPU buffers are opaque
    # handles, not a flat address space
    def alloc_mem(self, nbytes: int, alignment: int = 64) -> int:
        raise ACCLError("TPU backend allocates via create_buffer only")

    def free_mem(self, address: int) -> None:
        pass

    def read_mem(self, address: int, nbytes: int) -> bytes:
        buf, off = self._engine.resolve(self._rank, address)
        if buf is None:
            raise ACCLError(f"read_mem: unknown address {address:#x}")
        raw = np.asarray(buf.dev).tobytes()
        start = off * buf.host.itemsize
        return raw[start:start + nbytes]

    def write_mem(self, address: int, data: bytes) -> None:
        import jax.numpy as jnp

        buf, off = self._engine.resolve(self._rank, address)
        if buf is None:
            raise ACCLError(f"write_mem: unknown address {address:#x}")
        vals = np.frombuffer(data, dtype=buf.host.dtype)
        buf.set_dev_range(off, jnp.asarray(vals))

    def create_buffer(self, length: int, dtype: np.dtype) -> BaseBuffer:
        return self._engine.create_buffer(self._rank, length, dtype)

    def setup_rx_buffers(self, n_bufs: int, buf_size: int) -> None:
        pass  # no rx pool: ICI/XLA manage buffering

    def upload_communicator(self, comm: Communicator) -> int:
        return self._engine.set_comm(comm)

    def upload_arithconfig(self, cfg: ArithConfig) -> int:
        # registered so the gang can recover each call's wire dtype
        # (f16 vs bf16 compression pair) from the descriptor's arithcfg id
        return self._engine.register_arithcfg(cfg)

    def set_tuning(self, key: int, value: int) -> None:
        """TPU twin of the engine tuning registers (clear-error
        contract, constants.TuningKey): RING_THRESHOLD_BYTES is live —
        it moves the ring/HLO crossover the gang planner compiles
        against (`_gang_plan` keys its signature on it, so a write
        recompiles affected shapes) — and the flat-tree registers are
        stored as schedule hints (the XLA collective owns the schedule
        below the ring threshold).  Unknown keys raise an ACCLError
        naming the key and the known set."""
        from ..constants import (
            TPU_TUNING_KEYS,
            TuningKey,
            unknown_tuning_key_error,
        )

        if key not in TPU_TUNING_KEYS:
            raise unknown_tuning_key_error(key, TPU_TUNING_KEYS, "tpu")
        if key == int(TuningKey.RING_THRESHOLD_BYTES):
            self._engine.ring_threshold_bytes = int(value)
        else:
            self._engine.tuning_registers[int(key)] = int(value)

    def push_krnl(self, data: np.ndarray) -> None:
        self._engine.push_krnl(self._rank, data)

    def pop_stream(self, strm: int, nbytes: int, timeout_s: float = 10.0):
        arr = self._engine.pop_stream(self._rank, strm, timeout_s)
        return None if arr is None else arr.tobytes()[:nbytes]

    # -- persistent plans (accl_tpu/plans.py): every rank shares the
    # in-process engine, so the ring IS the shared submission/
    # completion structure — arm rendezvouses the world's captures,
    # replay is a sequence-counter bump on the shared ring
    def arm_plan(self, calls, expected, timeout_s: float):
        return self._engine.arm_plan(self._rank, calls, expected,
                                     timeout_s)

    def plan_replay(self, ring, run_async: bool = False,
                    timeout_s: float = 60.0):
        return self._engine.ring_replay(self._rank, ring, run_async,
                                        timeout_s)

    def plan_wait(self, ring, token, timeout_s: float) -> bool:
        return self._engine.ring_wait(ring, token, timeout_s)

    def invalidate_plans(self, comm_id: int = -1) -> None:
        self._engine.invalidate_rings(
            None if comm_id < 0 else comm_id,
            "invalidated by the driver (shrink/grow/reset)")

    def plan_release(self, ring) -> None:
        """Release a dead plan's ring (driver finalizer path)."""
        self._engine.release_ring(ring)

    # -- resilience: every rank shares one in-process engine, so a
    # single abort covers the whole world (no wire propagation needed)
    def abort_comm(self, comm_id: int, err_bits: int) -> bool:
        return self._engine.abort_comm(comm_id, err_bits)

    # -- elastic membership (r11) -------------------------------------
    def join_sync(self, sponsor_session: int,
                  timeout_s: float = 10.0) -> int:
        """In-process join state sync: the world-level scheduler IS the
        control plane, so the wire exchange of the emulator rung
        collapses to a gang-table rebuild for any comm this view's
        driver will re-adopt — epochs/fences are already shared.
        Always succeeds (0): the sponsor cannot be deaf in-process."""
        return 0

    def comm_count(self) -> int:
        return self._engine.comm_count()

    def export_join_state(self, comm_id: int = 0) -> dict:
        return self._engine.export_join_state(comm_id)

    def rebuild_gang_tables(self, comm_id: int) -> int:
        return self._engine.rebuild_gang_tables(comm_id)

    def reset_errors(self) -> None:
        self._engine.reset_comm_errors()

    def close(self) -> None:
        pass


class TpuWorld:
    """N ranks over the TPU backend with the same harness surface as
    EmuWorld: per-rank ACCL handles and `run(fn)` concurrency."""

    def __init__(self, nranks: int, devices=None, **_ignored):
        self.nranks = nranks
        self.engine = TpuEngine(nranks, devices)
        self.devices = [TpuDeviceView(self.engine, r) for r in range(nranks)]
        self.accls = [ACCL(d) for d in self.devices]
        self._pool = ThreadPoolExecutor(max_workers=nranks)
        ranks = [Rank(ip="127.0.0.1", port=0, session=r) for r in range(nranks)]
        for r, a in enumerate(self.accls):
            a.initialize(ranks, r)
        # hang watchdog over this world's per-rank flight recorders
        # (no-op under ACCL_WATCHDOG_TIMEOUT=0 / ACCL_FLIGHT=0)
        self.engine.start_watchdog(
            [a.flight_recorder for a in self.accls
             if a.flight_recorder is not None])
        # engine telemetry sampler (r14): the shared TpuEngine is one
        # stats source — polling it per rank would just re-read the
        # same counters
        from ..observability import telemetry as _telemetry

        self.telemetry = _telemetry.sampler_from_env(
            [self.devices[0].engine_stats], name="accl-tpu",
            link_sources=[(r, d.link_stats)
                          for r, d in enumerate(self.devices)])
        # online tuner (r19): same world-level arm as EmuWorld —
        # ACCL_TUNE_ONLINE=1 starts the live retune loop, unset
        # constructs nothing (bit-identical dispatch)
        from ..tuning import online as _online

        self.online_tuner = _online.ensure_online_tuner_from_env(self)

    def run(self, fn: Callable, *args) -> list:
        futures = [self._pool.submit(fn, self.accls[r], r, *args)
                   for r in range(self.nranks)]
        return [f.result(timeout=300) for f in futures]

    def link_stats(self) -> dict:
        """Per-rank link rows (r15): rank -> (comm, peer) counter rows
        from the gang scheduler's wire twin."""
        return {r: d.link_stats() for r, d in enumerate(self.devices)}

    def link_matrix(self, comm: int = 0,
                    tenant: Optional[str] = None) -> dict:
        """World-level P×P link traffic matrix (same schema as
        EmuWorld.link_matrix — observability/telemetry.link_matrix).
        ``tenant`` (r20) slices by tenant label instead: the union of
        every communicator labeled that tenant across the drivers."""
        from ..observability import telemetry as _telemetry

        if tenant is not None:
            comms = set()
            for a in self.accls:
                comms.update(a.tenant_comm_ids(tenant))
            doc = _telemetry.link_matrix(self.link_stats(),
                                         nranks=self.nranks, comms=comms)
            doc["tenant"] = tenant
            return doc
        return _telemetry.link_matrix(self.link_stats(),
                                      nranks=self.nranks, comm=comm)

    def close(self) -> None:
        if getattr(self, "online_tuner", None) is not None:
            from ..tuning import online as _online

            if _online.online_tuner() is self.online_tuner:
                _online.stop_online_tuner()
            else:
                self.online_tuner.stop()
            self.online_tuner = None
        if self.telemetry is not None:
            self.telemetry.stop()
            self.telemetry = None
        self.engine.shutdown()
        self._pool.shutdown(wait=False)

    def __enter__(self) -> "TpuWorld":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
