"""Emulator backend: the native C++ collective engine over ctypes.

Reference analog: `SimDevice`, which forwards call descriptors and buffer
sync to the `cclo_emu` emulator process over ZMQ (driver/xrt/src/
simdevice.cpp:38-64, test/model/emulator/cclo_emu.cpp).  Here the
emulator is an in-process native library (`native/libacclemu.so`): a
per-rank engine thread runs the collective algorithms against a CPU
dataplane and an inproc or TCP socket transport.

`EmuWorld` is the test harness equivalent of the reference's
one-emulator-per-MPI-rank bring-up (test/host/xrt/src/utility.cpp:26-70):
it creates N ranks in one process and runs per-rank driver code on a
thread pool, so the MPI-style test corpus ports directly.
"""
from __future__ import annotations

import ctypes
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Sequence

import numpy as np

from ..accl import ACCL, default_timeout
from ..arithconfig import ArithConfig
from ..buffer import BaseBuffer, EmuBuffer, EmuBufferP2P
from ..communicator import Communicator, Rank
from ..constants import ACCLError, CCLOCall, ErrorCode
from ..observability import flight as _flight
from ..observability import health as _health
from ..observability import trace as _trace
from ..request import Request
from ..utils.logging import get_logger
from .base import CCLODevice

# Sanitizer lane selection (docs/static_analysis.md "Native sanitizer
# lanes"): ACCL_SANITIZER=asan|ubsan|tsan loads the instrumented twin
# built by `ACCL_SANITIZER=<lane> make -C native`; ACCL_NATIVE_LIB
# overrides the path outright (a prebuilt artifact in CI).  NB the
# asan/tsan lanes need their runtime preloaded into the (uninstrumented)
# python — see the docs for the LD_PRELOAD line.
_SANITIZER = os.environ.get("ACCL_SANITIZER", "").strip()
_LIB_NAME = f"libacclemu_{_SANITIZER}.so" if _SANITIZER else "libacclemu.so"
_LIB_PATH = os.environ.get("ACCL_NATIVE_LIB") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    _LIB_NAME,
)

_lib = None


def _build_lib_if_stale() -> None:
    """Build (or rebuild) the native engine when the .so is missing or
    older than any of its sources, so a fresh checkout and an edited
    engine both work without a manual `make -C native` step."""
    import glob
    import subprocess

    if os.environ.get("ACCL_NATIVE_LIB"):
        return  # explicit artifact: never rebuild over it
    native_dir = os.path.dirname(_LIB_PATH)
    sources = glob.glob(os.path.join(native_dir, "src", "*.cpp")) + glob.glob(
        os.path.join(native_dir, "src", "*.hpp")) + [
        os.path.join(native_dir, "Makefile")
    ]
    if os.path.exists(_LIB_PATH):
        lib_mtime = os.path.getmtime(_LIB_PATH)
        if all(os.path.getmtime(s) <= lib_mtime for s in sources):
            return
    # serialize concurrent builders (e.g. parallel CI jobs sharing one
    # checkout) so two `make` runs can't corrupt the same .so
    lock_path = os.path.join(native_dir, ".build.lock")
    with open(lock_path, "w") as lock:
        try:
            import fcntl

            fcntl.flock(lock, fcntl.LOCK_EX)
        except ImportError:  # pragma: no cover (non-POSIX)
            pass
        # the build must not inherit a sanitizer runtime: under the ASan
        # lane LD_PRELOAD leaks into make/g++ and LeakSanitizer fails
        # the COMPILER with its own (irrelevant) leaks
        env = dict(os.environ)
        env.pop("LD_PRELOAD", None)
        env["ASAN_OPTIONS"] = "detect_leaks=0"
        try:
            proc = subprocess.run(["make", "-C", native_dir],
                                  capture_output=True, text=True, env=env)
        except FileNotFoundError as e:
            raise ACCLError(
                f"native engine not built and `make` unavailable: {e} "
                f"(build {_LIB_PATH} manually)") from e
        if proc.returncode != 0:
            raise ACCLError(
                f"native engine build failed:\n{proc.stdout}\n{proc.stderr}")


def _load_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    _build_lib_if_stale()
    if not os.path.exists(_LIB_PATH):
        raise ACCLError(
            f"native engine not built: {_LIB_PATH} missing (run `make -C native`)"
        )
    lib = ctypes.CDLL(_LIB_PATH)
    u64, u32, i32 = ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int
    p = ctypes.c_void_p
    lib.accl_world_create.restype = p
    lib.accl_world_create.argtypes = [i32, u64]
    lib.accl_world_create_tcp.restype = p
    lib.accl_world_create_tcp.argtypes = [i32, i32, i32, u64]
    lib.accl_world_create_dgram.restype = p
    lib.accl_world_create_dgram.argtypes = [i32, u64, u32, u32]
    lib.accl_dgram_fault.argtypes = [p, u32]
    lib.accl_world_create_rdma.restype = p
    lib.accl_world_create_rdma.argtypes = [i32, u64]
    lib.accl_dump_qps.argtypes = [p, i32, ctypes.c_char_p, i32]
    lib.accl_world_destroy.argtypes = [p]
    lib.accl_world_shutdown.argtypes = [p]
    lib.accl_cfg_rx.argtypes = [p, i32, i32, u64]
    lib.accl_set_comm.argtypes = [p, i32, ctypes.POINTER(u32), i32]
    lib.accl_set_arithcfg.argtypes = [p, i32, ctypes.POINTER(u32), i32]
    lib.accl_set_tuning.argtypes = [p, i32, u32, u32]
    lib.accl_alloc.restype = u64
    lib.accl_alloc.argtypes = [p, i32, u64, u64]
    lib.accl_alloc_host.restype = u64
    lib.accl_alloc_host.argtypes = [p, i32, u64, u64]
    lib.accl_alloc_p2p.restype = u64
    lib.accl_alloc_p2p.argtypes = [p, i32, u64, u64]
    lib.accl_free_p2p.argtypes = [p, i32, u64]
    lib.accl_mem_ptr.restype = ctypes.c_void_p
    lib.accl_mem_ptr.argtypes = [p, i32, u64, u64]
    lib.accl_tx_stats.argtypes = [p, i32, ctypes.POINTER(u64),
                                  ctypes.POINTER(u64)]
    lib.accl_open_port.restype = i32
    lib.accl_open_port.argtypes = [p, i32]
    lib.accl_open_con.restype = i32
    lib.accl_open_con.argtypes = [p, i32, i32]
    lib.accl_close_con.restype = i32
    lib.accl_close_con.argtypes = [p, i32, i32]
    lib.accl_free.argtypes = [p, i32, u64]
    lib.accl_read_mem.argtypes = [p, i32, u64, ctypes.c_void_p, u64]
    lib.accl_write_mem.argtypes = [p, i32, u64, ctypes.c_void_p, u64]
    lib.accl_start_call.restype = u64
    lib.accl_start_call.argtypes = [p, i32, ctypes.POINTER(u32)]
    lib.accl_poll_call.argtypes = [p, i32, u64, ctypes.POINTER(u32),
                                   ctypes.POINTER(ctypes.c_double)]
    lib.accl_wait_call.argtypes = [p, i32, u64, i32, ctypes.POINTER(u32),
                                   ctypes.POINTER(ctypes.c_double)]
    lib.accl_push_krnl.argtypes = [p, i32, ctypes.c_void_p, u64]
    lib.accl_pop_stream.argtypes = [p, i32, u32, ctypes.c_void_p, u64,
                                    ctypes.POINTER(u64), i32]
    lib.accl_dump_rx.argtypes = [p, i32, ctypes.c_char_p, i32]
    lib.accl_inject_fault.argtypes = [p, i32, u32]
    # resilience control plane (retransmission / abort / shrink / chaos)
    lib.accl_set_resilience.restype = i32
    lib.accl_set_resilience.argtypes = [p, i32, u32, u32]
    lib.accl_abort.restype = i32
    lib.accl_abort.argtypes = [p, i32, i32, u32]
    lib.accl_reset_errors.restype = i32
    lib.accl_reset_errors.argtypes = [p, i32]
    lib.accl_set_chaos.restype = i32
    lib.accl_set_chaos.argtypes = [p, i32, u64, u32, u32, u32, u32, u32, u32]
    lib.accl_chaos_kill.restype = i32
    lib.accl_chaos_kill.argtypes = [p, i32]
    lib.accl_probe_liveness.restype = i32
    lib.accl_probe_liveness.argtypes = [p, i32, i32, u32, ctypes.POINTER(u64)]
    lib.accl_resilience_stats.argtypes = [p, i32, ctypes.POINTER(u64),
                                          ctypes.POINTER(u64),
                                          ctypes.POINTER(u64),
                                          ctypes.POINTER(u64)]
    # elastic membership (r11): live rank join
    lib.accl_world_add_rank.restype = i32
    lib.accl_world_add_rank.argtypes = [p]
    lib.accl_join_sync.restype = i32
    lib.accl_join_sync.argtypes = [p, i32, u32, i32]
    lib.accl_comm_count.restype = i32
    lib.accl_comm_count.argtypes = [p, i32]
    lib.accl_comm_epoch.restype = u32
    lib.accl_comm_epoch.argtypes = [p, i32, i32]
    lib.accl_join_stats.argtypes = [p, i32, ctypes.POINTER(u64),
                                    ctypes.POINTER(u64)]
    # persistent collective plans (r12): pre-marshaled descriptor ring
    i64 = ctypes.c_longlong
    lib.accl_plan_create.restype = i32
    lib.accl_plan_create.argtypes = [p, i32, ctypes.POINTER(u32), i32]
    lib.accl_plan_replay.restype = i64
    lib.accl_plan_replay.argtypes = [p, i32, i32]
    lib.accl_plan_poll.restype = i32
    lib.accl_plan_poll.argtypes = [p, i32, i64, ctypes.POINTER(u32),
                                   ctypes.POINTER(ctypes.c_double)]
    lib.accl_plan_wait.restype = i32
    lib.accl_plan_wait.argtypes = [p, i32, i64, i32, ctypes.POINTER(u32),
                                   ctypes.POINTER(ctypes.c_double)]
    lib.accl_plan_invalidate.restype = i32
    lib.accl_plan_invalidate.argtypes = [p, i32, i32]
    lib.accl_plan_count.restype = i32
    lib.accl_plan_count.argtypes = [p, i32]
    lib.accl_plan_release.restype = i32
    lib.accl_plan_release.argtypes = [p, i32, i32]
    # wire-protocol correctness surface (r13): raw-frame ingest, frame
    # counters, egress frame tap (fuzz seed-corpus capture)
    lib.accl_engine_ingest_bytes.restype = i32
    lib.accl_engine_ingest_bytes.argtypes = [p, i32, ctypes.c_char_p, u64]
    lib.accl_frame_stats.argtypes = [p, i32, ctypes.POINTER(u64),
                                     ctypes.POINTER(u64)]
    lib.accl_frame_tap.restype = i32
    lib.accl_frame_tap.argtypes = [p, i32, i32]
    lib.accl_frame_tap_count.restype = i32
    lib.accl_frame_tap_count.argtypes = [p, i32]
    lib.accl_frame_tap_read.restype = i32
    lib.accl_frame_tap_read.argtypes = [p, i32, i32, ctypes.c_void_p, i32]
    lib.accl_frame_tap_drain.restype = i32
    lib.accl_frame_tap_drain.argtypes = [p, i32, ctypes.c_void_p, i32]
    # engine telemetry snapshot (r14): versioned flat-array stats plane
    lib.accl_engine_stats_version.restype = i32
    lib.accl_engine_stats_version.argtypes = []
    lib.accl_engine_stats.restype = i32
    lib.accl_engine_stats.argtypes = [p, i32, ctypes.POINTER(u64), i32]
    # per-link wire telemetry (r15): flat (comm, peer) counter rows
    lib.accl_engine_link_stats_stride.restype = i32
    lib.accl_engine_link_stats_stride.argtypes = []
    lib.accl_engine_link_stats.restype = i32
    lib.accl_engine_link_stats.argtypes = [p, i32, ctypes.POINTER(u64),
                                           i32]
    _lib = lib
    return lib


def _words(vals: Sequence[int]):
    arr = (ctypes.c_uint32 * len(vals))(*[v & 0xFFFFFFFF for v in vals])
    return arr


def _join_waiters(devices, timeout_s: float = 10.0) -> int:
    """Join every tracked waiter thread of `devices` (bounded); returns
    how many were STILL alive afterwards.  Called between world
    shutdown (which makes their FFI waits return promptly) and world
    destroy (which frees the memory they were polling)."""
    import time

    deadline = time.monotonic() + timeout_s
    stuck = 0
    for d in devices:
        for t in list(getattr(d, "_waiters", ())):
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                stuck += 1
    return stuck


# ---------------------------------------------------------------------------
# interpreter-exit safety net: close every still-open world BEFORE the
# interpreter (and then the C runtime) starts tearing the process down.
# A world leaked by test code keeps native engine threads running into
# __cxa_finalize — where the library's static destructors run out from
# under them (the r13 suite-exit segfault class).  atexit handlers run
# LIFO, so registering at import time (before any ThreadPoolExecutor
# exists) means this fires AFTER user code but BEFORE
# concurrent.futures' own exit hook joins its workers.
# ---------------------------------------------------------------------------
import atexit  # noqa: E402 — grouped with its registry on purpose
import weakref  # noqa: E402

_live_worlds: "weakref.WeakSet" = weakref.WeakSet()


def _close_live_worlds() -> None:  # pragma: no cover — exit path
    for w in list(_live_worlds):
        try:
            w.close()
        except Exception:  # noqa: BLE001 — never let cleanup raise at exit
            pass


atexit.register(_close_live_worlds)


class EmuDevice(CCLODevice):
    """One rank's handle on the native engine."""

    def __init__(self, world_handle: ctypes.c_void_p, rank: int,
                 lib: ctypes.CDLL, call_timeout_s: float = 60.0):
        self._w = world_handle
        self._rank = rank
        self._lib = lib
        self._timeout_ms = int(call_timeout_s * 1000)
        # arm the NACK retransmission lane from the env policy
        # (ACCL_RETRY_MAX / ACCL_RETRY_BASE_US; worlds may override)
        from ..resilience.retry import RetryPolicy

        pol = RetryPolicy.from_env()
        self.set_resilience(pol.max_retries, pol.base_us)
        #: True while every rank of this world lives in this process
        #: (EmuWorld); EmuRankTcp clears it — its peers are other
        #: processes (or sibling worlds) the in-process sanitizer
        #: exchange can never pair with
        self.shares_process_world = True
        #: last frame-counter values already published to the metrics
        #: registry (frame_stats publishes monotonic deltas)
        self._frames_published: dict = {}
        #: live waiter threads (one per in-flight engine call).  World
        #: close() joins these AFTER accl_world_shutdown made their FFI
        #: waits return and BEFORE accl_world_destroy frees the
        #: engines — the ordering that fixes the suite-exit segfault
        #: (a waiter scheduled late dereferencing a nulled/freed world).
        self._waiters: set = set()
        #: serializes call submission against close(): start() snapshots
        #: the world handle, submits, and registers its waiter under
        #: this lock; close() nulls the handle under the same lock, so
        #: a submission either completes registration (and is joined
        #: before destroy) or observes the nulled handle — there is no
        #: window where a stale handle outlives the world
        self._lifecycle = threading.Lock()

    def sanitizer_domain(self):
        """The native world handle identifies the in-process gang for
        the sanitizer's cross-rank fingerprint exchange (one EmuWorld ==
        one engine world == one domain)."""
        if self.shares_process_world and self._w:
            return ("emu", int(self._w))
        return None

    # -- call path ----------------------------------------------------
    def start(self, call: CCLOCall, request: Request) -> None:
        # the native engine owns the session send/recv + rendezvous
        # retry loop below this point, so the span's device window is
        # the descriptor-post → engine-completion interval (its interior
        # breakdown is the engine's cycle-count duration, stamped on the
        # request as duration_ns)
        span = request.trace
        rec = request.flight
        if rec is not None:
            rec.mark_dispatched("emu", _trace.now_ns())
        if span is not None:
            span.lane = "emu"
            span.t_dispatch = span.t_device_begin = _trace.now_ns()
        # snapshot + submit + waiter registration happen atomically
        # against close() (the _lifecycle contract above): after this
        # block either the call is tracked (close joins its waiter
        # before destroying) or the handle was already None and the
        # request fails fast.  The C side additionally null-guards, so
        # even an untracked straggler gets a clean error, never a
        # dereference.
        with self._lifecycle:
            world = self._w
            if world is None:
                request.complete(int(ErrorCode.COMM_ABORTED
                                     | ErrorCode.RANK_FAILED), 0.0)
                return
            call_id = self._lib.accl_start_call(world, self._rank,
                                                _words(call.to_words()))
            t = threading.Thread(target=lambda: waiter(), daemon=True)
            self._waiters.add(t)

        def waiter():
            try:
                ret = ctypes.c_uint32(0)
                dur = ctypes.c_double(0.0)
                ok = self._lib.accl_wait_call(world, self._rank, call_id,
                                              self._timeout_ms,
                                              ctypes.byref(ret),
                                              ctypes.byref(dur))
                if span is not None:
                    span.t_device_end = _trace.now_ns()
                if ok:
                    request.complete(ret.value, dur.value)
                else:
                    from ..constants import ErrorCode

                    get_logger("accl_tpu.emu", rank=self._rank).warning(
                        "engine wait timed out after %d ms: %s%s",
                        self._timeout_ms, request.description,
                        request.flight_info())
                    request.complete(int(ErrorCode.DMA_TIMEOUT_ERROR), 0.0)
            finally:
                self._waiters.discard(threading.current_thread())

        t.start()

    # -- device memory ------------------------------------------------
    def alloc_mem(self, nbytes: int, alignment: int = 64) -> int:
        addr = self._lib.accl_alloc(self._w, self._rank, nbytes, alignment)
        if addr == 0:
            raise ACCLError("emulator device memory exhausted")
        return addr

    def free_mem(self, address: int) -> None:
        self._lib.accl_free(self._w, self._rank, address)

    def free_mem_p2p(self, address: int) -> None:
        self._lib.accl_free_p2p(self._w, self._rank, address)

    def read_mem(self, address: int, nbytes: int) -> bytes:
        buf = ctypes.create_string_buffer(nbytes)
        rc = self._lib.accl_read_mem(self._w, self._rank, address, buf, nbytes)
        if rc != 0:
            raise ACCLError(f"read_mem({address:#x}, {nbytes}) out of range")
        return buf.raw

    def write_mem(self, address: int, data: bytes) -> None:
        rc = self._lib.accl_write_mem(self._w, self._rank, address, data,
                                      len(data))
        if rc != 0:
            raise ACCLError(f"write_mem({address:#x}, {len(data)}) out of range")

    # -- buffers ------------------------------------------------------
    def create_buffer(self, length: int, dtype: np.dtype,
                      host_only: bool = False) -> BaseBuffer:
        host = np.zeros(length, dtype=dtype)
        if host_only:
            addr = self._lib.accl_alloc_host(self._w, self._rank,
                                             max(host.nbytes, 64), 64)
            if addr == 0:
                raise ACCLError("emulator host-buffer region exhausted")
            return EmuBuffer(host, self, addr, host_only=True)
        addr = self.alloc_mem(max(host.nbytes, 64))
        return EmuBuffer(host, self, addr)

    def create_buffer_p2p(self, length: int, dtype: np.dtype) -> BaseBuffer:
        """Peer-addressable buffer (reference FPGABufferP2P): the host
        view is a direct MAPPING of engine devicemem (zero-copy, no
        sync), and the span is registered peer-writable — an in-process
        peer's rendezvous one-sided write lands in it by direct memcpy,
        bypassing the wire (native/src/engine.cpp rndzv_send fast
        path)."""
        nbytes = max(int(np.dtype(dtype).itemsize) * length, 64)
        addr = self._lib.accl_alloc_p2p(self._w, self._rank, nbytes, 64)
        if addr == 0:
            raise ACCLError("emulator device memory exhausted (p2p)")
        ptr = self._lib.accl_mem_ptr(self._w, self._rank, addr, nbytes)
        if not ptr:
            raise ACCLError("p2p mapping failed")
        raw = (ctypes.c_uint8 * nbytes).from_address(ptr)
        host = np.frombuffer(raw, dtype=dtype, count=length)
        return EmuBufferP2P(host, self, addr)

    # -- session lifecycle (reference open_port/open_con/close_con over
    # tcp_session_handler; accl.hpp:1069-1083).  TCP worlds really
    # connect/tear down; inproc/datagram transports succeed as no-ops. --
    def open_port(self) -> int:
        return int(self._lib.accl_open_port(self._w, self._rank))

    def open_con(self, comm_id: int) -> int:
        return int(self._lib.accl_open_con(self._w, self._rank, comm_id))

    def close_con(self, comm_id: int) -> int:
        return int(self._lib.accl_close_con(self._w, self._rank, comm_id))

    def tx_stats(self) -> tuple:
        """Egress (messages, payload_bytes) handed to the transport —
        the observable that proves the p2p path bypassed the wire."""
        msgs = ctypes.c_uint64(0)
        pay = ctypes.c_uint64(0)
        self._lib.accl_tx_stats(self._w, self._rank, ctypes.byref(msgs),
                                ctypes.byref(pay))
        return int(msgs.value), int(pay.value)

    # -- configuration ------------------------------------------------
    def setup_rx_buffers(self, n_bufs: int, buf_size: int) -> None:
        self._lib.accl_cfg_rx(self._w, self._rank, n_bufs, buf_size)

    def upload_communicator(self, comm: Communicator) -> int:
        w = comm.to_words()
        return self._lib.accl_set_comm(self._w, self._rank, _words(w), len(w))

    def upload_arithconfig(self, cfg: ArithConfig) -> int:
        w = cfg.to_words()
        return self._lib.accl_set_arithcfg(self._w, self._rank, _words(w),
                                           len(w))

    def set_tuning(self, key: int, value: int) -> None:
        """Write a flat-tree tuning register (reference:
        configure_tuning_parameters, accl.cpp:1214-1224; keys named in
        constants.TuningKey).  Unknown keys raise an ACCLError naming
        the key and the engine's known set — the engine rejects them
        instead of silently writing nothing (clear-error contract)."""
        from ..constants import EMU_TUNING_KEYS, unknown_tuning_key_error

        rc = self._lib.accl_set_tuning(self._w, self._rank, key, value)
        if rc == -2 or (rc != 0 and key not in EMU_TUNING_KEYS):
            raise unknown_tuning_key_error(key, EMU_TUNING_KEYS, "emu")
        if rc != 0:
            raise ACCLError(f"set_tuning({key}, {value}) failed (rc={rc})")

    # -- streams (PL-kernel equivalent) -------------------------------
    def push_krnl(self, data: np.ndarray) -> None:
        """Feed operand bytes into the engine's compute-kernel input
        stream (OP0_STREAM source; reference data_to_cclo port)."""
        b = np.ascontiguousarray(data).tobytes()
        self._lib.accl_push_krnl(self._w, self._rank, b, len(b))

    def pop_stream(self, strm: int, nbytes: int,
                   timeout_s: float = 10.0) -> Optional[bytes]:
        """Pull one message from a compute stream (data_from_cclo port)."""
        buf = ctypes.create_string_buffer(nbytes)
        got = ctypes.c_uint64(0)
        ok = self._lib.accl_pop_stream(self._w, self._rank, strm, buf, nbytes,
                                       ctypes.byref(got),
                                       int(timeout_s * 1000))
        return buf.raw[: got.value] if ok else None

    def dump_rx_buffers(self) -> str:
        out = ctypes.create_string_buffer(65536)
        self._lib.accl_dump_rx(self._w, self._rank, out, 65536)
        return out.value.decode()

    #: fault kinds for inject_fault (one-shot, next egress message)
    FAULT_DROP = 1
    FAULT_DUPLICATE = 2
    FAULT_CORRUPT_SEQ = 3
    FAULT_DELAY = 4

    def inject_fault(self, kind: int) -> None:
        """Arm a one-shot egress fault on this rank's engine — sugar
        over the seeded chaos funnel (forces its next draw): drop /
        duplicate / corrupt-seqn / delay, resolved in the same engine
        switch the probabilistic plan uses (SURVEY §5)."""
        rc = self._lib.accl_inject_fault(self._w, self._rank, kind)
        if rc != 0:
            raise ACCLError(f"inject_fault({kind}) failed for rank "
                            f"{self._rank}")

    # -- resilience (accl_tpu/resilience; docs/fault_tolerance.md) ----
    def set_resilience(self, retry_max: int, retry_base_us: int) -> None:
        """Configure the NACK retransmission lane (0 retries = off)."""
        self._lib.accl_set_resilience(self._w, self._rank,
                                      max(0, int(retry_max)),
                                      max(1, int(retry_base_us)))

    def abort_comm(self, comm_id: int, err_bits: int) -> bool:
        """Epoch-tagged abort of a communicator, propagated to every
        peer through the control plane; returns True (engine handled
        the fan-out and pending-call finalization)."""
        rc = self._lib.accl_abort(self._w, self._rank, comm_id,
                                  err_bits & 0xFFFFFFFF)
        if rc != 0:
            raise ACCLError(f"abort(comm {comm_id}) failed for rank "
                            f"{self._rank}")
        return True

    def reset_errors(self) -> None:
        """Seqn resync + transient-state drain after a classified fault
        (collective: every rank of a quiesced world calls it)."""
        self._lib.accl_reset_errors(self._w, self._rank)

    def set_chaos(self, seed: int, drop_ppm: int, dup_ppm: int,
                  delay_ppm: int, delay_us: int, corrupt_ppm: int,
                  slow_us: int) -> None:
        """Arm the seeded probabilistic chaos plan on this rank."""
        self._lib.accl_set_chaos(self._w, self._rank, seed, drop_ppm,
                                 dup_ppm, delay_ppm, delay_us, corrupt_ppm,
                                 slow_us)

    def kill(self) -> None:
        """Kill-rank chaos: this engine goes silent (egress dropped,
        ingress deaf) and aborts its local comms with RANK_FAILED."""
        self._lib.accl_chaos_kill(self._w, self._rank)

    def probe_liveness(self, comm_id: int, size: int,
                       window_s: float = 1.0) -> list:
        """Heartbeat-probe every peer of a communicator; returns a
        per-comm-local-rank alive list (local rank always True)."""
        bm = ctypes.c_uint64(0)
        rc = self._lib.accl_probe_liveness(
            self._w, self._rank, comm_id, int(window_s * 1e6),
            ctypes.byref(bm))
        if rc != 0:
            raise ACCLError(f"probe_liveness(comm {comm_id}) failed")
        return [bool(bm.value >> i & 1) for i in range(size)]

    def resilience_stats(self) -> dict:
        """Engine-side recovery counters: retransmitted segments, NACKs
        sent/received, epoch-fenced ingress drops."""
        vals = [ctypes.c_uint64(0) for _ in range(4)]
        self._lib.accl_resilience_stats(self._w, self._rank,
                                        *[ctypes.byref(v) for v in vals])
        keys = ("retrans_sent", "nacks_tx", "nacks_rx", "fenced_drops")
        return dict(zip(keys, (int(v.value) for v in vals)))

    def engine_stats(self) -> dict:
        """Full engine telemetry snapshot (r14): retransmit-store depth/
        evictions, NACK counters, rx-pool occupancy + high-water,
        egress/ingress queue depths, seek-miss rate inputs, plan table/
        token state, wire accept/reject, tx traffic, join counters —
        ONE FFI for the whole plane (the sampler's poll body).  Decoded
        through the versioned field schema so a newer engine's extra
        fields surface as ``unknown_field_<i>`` instead of vanishing."""
        from ..observability import telemetry as _telemetry

        if not self._w:
            raise ACCLError("engine_stats: world is closed")
        cap = max(64, len(_telemetry.ENGINE_STATS_FIELDS_V1))
        buf = (ctypes.c_uint64 * cap)()
        total = int(self._lib.accl_engine_stats(self._w, self._rank,
                                                buf, cap))
        if total < 0:
            raise ACCLError(f"engine_stats failed for rank {self._rank}")
        version = int(self._lib.accl_engine_stats_version())
        return _telemetry.decode_engine_stats(
            buf[:min(total, cap)], version=version, total_fields=total)

    def link_stats(self) -> list:
        """Per-(comm, peer) wire counters (r15): tx/rx messages+bytes,
        retransmits served, NACKs both directions, epoch-fenced drops,
        and seek count/blocked-wait per peer — ONE FFI for the whole
        link plane, decoded through the strict stride-checked schema
        (LINK_STATS_FIELDS_V2).  Returns a list of row dicts; peers are
        comm-local ranks (global ranks on comm 0)."""
        from ..observability import telemetry as _telemetry

        if not self._w:
            raise ACCLError("link_stats: world is closed")
        stride = int(self._lib.accl_engine_link_stats_stride())
        expect = len(_telemetry.LINK_STATS_FIELDS_V2)
        if stride != expect:
            # deterministic stride agreement BEFORE any slicing: the
            # decoder's whole-number-of-rows check alone would pass by
            # coincidence whenever rows * new_stride happens to divide
            # by the old one
            raise ACCLError(
                f"link_stats: engine row stride {stride} != this "
                f"build's schema ({expect} fields) — mixed-version "
                f"world; refusing to mis-slice")
        total = int(self._lib.accl_engine_link_stats(
            self._w, self._rank, None, 0))
        if total < 0:
            raise ACCLError(f"link_stats failed for rank {self._rank}")
        if total == 0:
            return []
        # headroom: rows minted between the size probe and the read
        cap = total + 16 * stride
        buf = (ctypes.c_uint64 * cap)()
        got = int(self._lib.accl_engine_link_stats(self._w, self._rank,
                                                   buf, cap))
        if got < 0:
            raise ACCLError(f"link_stats failed for rank {self._rank}")
        return _telemetry.decode_link_stats(buf[:min(got, cap)])

    # -- persistent collective plans (r12) ----------------------------
    def arm_plan(self, calls, expected=None, timeout_s: float = 30.0):
        """Pre-marshal a captured descriptor stream into the engine's
        plan storage: every 15-word descriptor is parsed ONCE here; a
        replay is a single FFI entry for the whole batch (no per-call
        Python marshaling, no per-call FFI).  Per-rank — the engine's
        own wire protocol pairs the gangs across ranks, exactly as it
        does for eager calls."""
        words: list = []
        for call in calls:
            words.extend(call.to_words())
        pid = int(self._lib.accl_plan_create(
            self._w, self._rank, _words(words), len(calls)))
        if pid < 0:
            raise ACCLError(
                "arm_plan: engine rejected the descriptor batch (a "
                "referenced communicator is aborted, or the batch is "
                "empty) — recover the world, then capture")
        return pid

    def plan_replay(self, plan_id: int, run_async: bool = False,
                    timeout_s: float = 60.0):
        """One replay of the armed batch.  Sync (default): blocks until
        every call completed and raises on a non-zero combined retcode.
        Async: returns the completion token for plan_wait."""
        token = int(self._lib.accl_plan_replay(self._w, self._rank,
                                               plan_id))
        if token == -2:
            raise ACCLError(
                "plan replay: plan invalidated by an abort/epoch "
                "fence/reset — re-capture on the recovered "
                "communicator",
                int(ErrorCode.COMM_ABORTED))
        if token < 0:
            raise ACCLError(f"plan replay: unknown plan id {plan_id}")
        if run_async:
            return token
        if not self.plan_wait(plan_id, token, timeout_s):
            raise ACCLError(
                f"plan replay timed out after {timeout_s:.0f}s")
        return None

    def plan_wait(self, plan_id: int, token: int,
                  timeout_s: float) -> bool:
        """Block until a replay token completes (False on timeout);
        raises the decoded engine error on a non-zero retcode."""
        from ..constants import error_code_to_str

        ret = ctypes.c_uint32(0)
        dur = ctypes.c_double(0.0)
        rc = int(self._lib.accl_plan_wait(
            self._w, self._rank, token, int(timeout_s * 1000),
            ctypes.byref(ret), ctypes.byref(dur)))
        if rc == 0:
            return False
        if rc < 0:
            raise ACCLError(f"plan replay: unknown token {token}")
        if ret.value != 0:
            raise ACCLError(
                f"plan replay failed: {error_code_to_str(ret.value)}",
                int(ret.value))
        return True

    def invalidate_plans(self, comm_id: int = -1) -> None:
        """Fence engine-side plans touching a comm (-1 = all) — the
        shrink/grow half of the eviction contract (abort and
        reset_errors fence inside the engine on their own)."""
        self._lib.accl_plan_invalidate(self._w, self._rank, comm_id)

    def plan_count(self) -> int:
        """Live (valid) engine-side plans — eviction introspection."""
        return int(self._lib.accl_plan_count(self._w, self._rank))

    def plan_release(self, plan_id: int) -> None:
        """Release a dead plan's engine-side storage.  Called from a
        GC finalizer, which may outlive the world — the null-handle
        guard keeps a post-teardown release a no-op instead of a
        use-after-free (EmuWorld.close nulls its devices' handles)."""
        if self._w:
            self._lib.accl_plan_release(self._w, self._rank, plan_id)

    # -- wire-protocol correctness surface (r13) ----------------------
    def ingest_bytes(self, frame: bytes) -> int:
        """Feed one raw wire frame (64-byte header + payload) through
        this engine's REAL ingress classification path, as if a peer's
        transport delivered it.  Returns 0 when the engine consumed it
        (or legally dropped it at the kill/epoch gate), 1 when it was
        rejected as malformed (counted in :meth:`frame_stats`).  The
        wire fuzzer's (scripts/fuzz_wire.py) one entry point."""
        rc = int(self._lib.accl_engine_ingest_bytes(
            self._w, self._rank, frame, len(frame)))
        if rc < 0:
            raise ACCLError(f"ingest_bytes failed for rank {self._rank}")
        return rc

    def frame_stats(self, publish: bool = True) -> dict:
        """Frames that passed structural validation vs frames rejected
        as malformed.  Each read publishes the deltas into the r8
        metrics registry (``wire/accepted_frames`` /
        ``wire/rejected_frames`` counters) so a scrape of /metrics sees
        the rejection rate without touching the FFI."""
        acc = ctypes.c_uint64(0)
        rej = ctypes.c_uint64(0)
        self._lib.accl_frame_stats(self._w, self._rank, ctypes.byref(acc),
                                   ctypes.byref(rej))
        stats = {"accepted_frames": int(acc.value),
                 "rejected_frames": int(rej.value)}
        if publish:
            from ..observability import metrics as _metrics

            reg = _metrics.default_registry()
            for key, val in stats.items():
                delta = val - self._frames_published.get(key, 0)
                if delta > 0:
                    reg.inc(f"wire/{key}", delta)
                    self._frames_published[key] = val
        return stats

    def frame_tap(self, on: bool = True) -> None:
        """Toggle the egress frame tap (bounded ring of the last 256
        staged frames, serialized wire framing)."""
        self._lib.accl_frame_tap(self._w, self._rank, 1 if on else 0)

    def tap_frames(self) -> list:
        """Drain the captured egress frames, oldest first, as raw
        bytes.  Atomic per batch (one native lock hold serializes a
        whole [len][bytes] run), so frames can never tear against live
        traffic rotating the ring; the tap is left EMPTY."""
        out: list = []
        buf = ctypes.create_string_buffer(1 << 20)
        while True:
            n = int(self._lib.accl_frame_tap_drain(self._w, self._rank,
                                                   buf, len(buf)))
            if n <= 0:
                break
            raw = buf.raw[:n]
            off = 0
            while off + 4 <= n:
                ln = int.from_bytes(raw[off:off + 4], "little")
                off += 4
                out.append(raw[off:off + ln])
                off += ln
        return out

    # -- elastic membership (r11): join control plane -----------------
    def join_sync(self, sponsor_session: int,
                  timeout_s: float = 10.0) -> int:
        """Joiner side of the Join/Welcome/StateSync exchange: sync
        per-comm epochs/abort fences + the comm-slot count from a live
        sponsor.  Returns 0, or -1 when the sponsor never answered
        (dead/killed — pick another survivor and retry)."""
        return int(self._lib.accl_join_sync(
            self._w, self._rank, sponsor_session,
            int(timeout_s * 1000)))

    def comm_count(self) -> int:
        """Comm slots (real + placeholder) this engine knows — lets the
        join path assert its id space really aligned."""
        return int(self._lib.accl_comm_count(self._w, self._rank))

    def comm_epoch(self, comm_id: int) -> int:
        """Current epoch of a comm slot (abort-fence introspection)."""
        return int(self._lib.accl_comm_epoch(self._w, self._rank,
                                             comm_id))

    def join_stats(self) -> dict:
        """Joins answered as sponsor / completed as joiner."""
        sponsored = ctypes.c_uint64(0)
        joined = ctypes.c_uint64(0)
        self._lib.accl_join_stats(self._w, self._rank,
                                  ctypes.byref(sponsored),
                                  ctypes.byref(joined))
        return {"sponsored": int(sponsored.value),
                "joined": int(joined.value)}

    def close(self) -> None:
        pass  # world teardown owns the native handle


class EmuRankTcp:
    """One rank over the TCP socket transport (one process — or thread —
    per rank; the reference's emulator-per-MPI-rank rung with ZMQ pub/sub
    replaced by length-prefixed TCP frames)."""

    def __init__(self, rank: int, nranks: int, base_port: int,
                 devmem_bytes: int = 64 << 20, n_egr_rx_bufs: int = 16,
                 egr_rx_buf_size: int = 1024,
                 max_eager_size: Optional[int] = None,
                 call_timeout_s: float = 60.0):
        self._lib = _load_lib()
        self.rank = rank
        self.nranks = nranks
        self._handle = self._lib.accl_world_create_tcp(rank, nranks, base_port,
                                                       devmem_bytes)
        if not self._handle:
            raise ACCLError(f"TCP emulator rank {rank} failed to start "
                            f"(port {base_port + rank} busy?)")
        # the driver-level sync wait gates the same calls as the engine's
        # receive timeout; the engine budget (ACCL_DEFAULT_TIMEOUT, µs)
        # must always fire FIRST so a stall surfaces as a decodable
        # RECEIVE_TIMEOUT_ERROR rather than an opaque driver wait failure
        # — clamp the driver budget above it
        call_timeout_s = max(call_timeout_s, default_timeout() / 1e6 + 5.0)
        self.device = EmuDevice(self._handle, rank, self._lib,
                                call_timeout_s=call_timeout_s)
        # one world handle per rank here (peers are separate processes
        # or sibling worlds): the in-process sanitizer exchange cannot
        # pair them — fall back to single-rank checks
        self.device.shares_process_world = False
        self.accl = ACCL(self.device)
        self.accl.call_timeout_s = call_timeout_s
        ranks = [Rank(ip="127.0.0.1", port=base_port + r, session=r,
                      max_segment_size=egr_rx_buf_size)
                 for r in range(nranks)]
        kwargs = {}
        if max_eager_size is not None:
            kwargs["max_eager_size"] = max_eager_size
        self.accl.initialize(ranks, rank, n_egr_rx_bufs=n_egr_rx_bufs,
                             egr_rx_buf_size=egr_rx_buf_size, **kwargs)
        # per-process telemetry sampler (multi-process worlds poll one
        # rank each; the scrape surface merges across processes)
        from ..observability import telemetry as _telemetry

        self.telemetry = _telemetry.sampler_from_env(
            [self.device.engine_stats], name=f"accl-tcp-r{rank}")
        _live_worlds.add(self)  # interpreter-exit safety net

    def close(self) -> None:
        if getattr(self, "telemetry", None) is not None:
            self.telemetry.stop()
            self.telemetry = None
        if self._handle:
            _flight.mark_event(self.accl.flight_recorder,
                               _flight.TEARDOWN_EVENT, -1, lane="lifecycle")
            # same shutdown -> null-under-lock -> join-waiters ->
            # destroy ordering as EmuWorld.close (the segfault fix)
            self._lib.accl_world_shutdown(self._handle)
            with self.device._lifecycle:
                self.device._w = None  # plan finalizers must no-op now
            stuck = _join_waiters([self.device])
            if stuck:
                get_logger("accl_tpu.emu").warning(
                    "tcp rank close: %d waiter thread(s) still alive "
                    "after shutdown — leaking the native world", stuck)
            else:
                self._lib.accl_world_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class EmuWorld:
    """N emulated ranks in one process.

    The MPI-replacement test harness: `run(fn)` executes `fn(accl, rank)`
    for every rank concurrently, mirroring how the reference test suite
    runs one driver per MPI rank against one emulator each.

    `transport` selects the wire rung: "inproc" (FIFO, synchronous hub),
    "dgram" (MTU fragmentation + deterministic out-of-order delivery +
    interleaved reassembly — the reference's UDP POE + depacketizer +
    rxbuf_session stack; see native/src/dgram.hpp), or "rdma" (queue
    pairs with an ordered control plane and a separate one-sided memory
    plane for rendezvous WRITEs — the CoyoteDevice rung; see
    native/src/rdma.hpp).
    """

    #: datagram fault kinds for inject_dgram_fault
    DGRAM_DROP_NEXT = 1
    DGRAM_DUP_NEXT = 2

    def __init__(self, nranks: int, devmem_bytes: int = 64 << 20,
                 n_egr_rx_bufs: int = 16, egr_rx_buf_size: int = 1024,
                 max_eager_size: Optional[int] = None,
                 max_rendezvous_size: Optional[int] = None,
                 initialize: bool = True, transport: str = "inproc",
                 mtu: int = 256, reorder_window: int = 8,
                 retry_max: Optional[int] = None,
                 retry_base_us: Optional[int] = None,
                 chaos=None):
        self._lib = _load_lib()
        self.nranks = nranks
        self._n_egr_rx_bufs = n_egr_rx_bufs
        self._egr_rx_buf_size = egr_rx_buf_size
        if transport == "dgram":
            self._handle = self._lib.accl_world_create_dgram(
                nranks, devmem_bytes, mtu, reorder_window)
        elif transport == "rdma":
            self._handle = self._lib.accl_world_create_rdma(
                nranks, devmem_bytes)
        elif transport == "inproc":
            self._handle = self._lib.accl_world_create(nranks, devmem_bytes)
        else:
            raise ACCLError(f"unknown transport {transport!r}")
        self.devices = [EmuDevice(self._handle, r, self._lib)
                        for r in range(nranks)]
        # retransmission policy: explicit args > ACCL_RETRY_* env >
        # defaults (the env policy was applied at device construction)
        if retry_max is not None or retry_base_us is not None:
            from ..resilience.retry import RetryPolicy

            env = RetryPolicy.from_env()
            rm = env.max_retries if retry_max is None else retry_max
            rb = env.base_us if retry_base_us is None else retry_base_us
            for d in self.devices:
                d.set_resilience(rm, rb)
        # seeded chaos plan: a ChaosPlan, a grammar string, or (by
        # default) whatever ACCL_CHAOS carries
        from ..resilience.chaos import ChaosPlan

        if isinstance(chaos, str):
            chaos = ChaosPlan.parse(chaos)
        if chaos is None:
            chaos = ChaosPlan.from_env()
        self.chaos_plan = chaos
        if chaos is not None:
            for r, d in enumerate(self.devices):
                chaos.apply(d, r)
        self.accls = [ACCL(d) for d in self.devices]
        self._pool = ThreadPoolExecutor(max_workers=nranks)
        if initialize:
            ranks = [
                Rank(ip="127.0.0.1", port=0, session=r,
                     max_segment_size=egr_rx_buf_size)
                for r in range(nranks)
            ]
            kwargs = {}
            if max_eager_size is not None:
                kwargs["max_eager_size"] = max_eager_size
            if max_rendezvous_size is not None:
                kwargs["max_rendezvous_size"] = max_rendezvous_size
            for r, a in enumerate(self.accls):
                a.initialize(ranks, r, n_egr_rx_bufs=n_egr_rx_bufs,
                             egr_rx_buf_size=egr_rx_buf_size, **kwargs)
        # hang watchdog over the per-rank flight recorders: the native
        # engine keeps its own gang state, so diagnosis here is purely
        # flight-ring based (which ranks have an in-flight gang call,
        # which never issued one).  Inert when ACCL_WATCHDOG_TIMEOUT=0,
        # ACCL_FLIGHT=0, or initialize was deferred (no recorders yet).
        # With ACCL_WATCHDOG_ACTION=abort a fire additionally aborts the
        # hung communicator (initiated from an arrived survivor) instead
        # of only dumping — the detect -> recover bridge.
        self.watchdog = _health.Watchdog(
            [a.flight_recorder for a in self.accls
             if a.flight_recorder is not None], name="accl-emu",
            abort_hook=self._watchdog_abort).start()
        # elastic membership (r11): the in-process join rendezvous —
        # replacement ranks announce here, the recovery supervisor's
        # grow policy discovers them (resilience/elastic.py)
        from ..resilience.elastic import MembershipBoard

        self.board = MembershipBoard()
        self.joiners: list = []
        # engine telemetry sampler (r14): polls every rank's native
        # stats snapshot into the shared registry as engine/* families.
        # None (no thread, zero work) unless ACCL_TELEMETRY_INTERVAL_MS
        # is set > 0.
        from ..observability import telemetry as _telemetry

        self.telemetry = _telemetry.sampler_from_env(
            [d.engine_stats for d in self.devices], name="accl-emu",
            link_sources=[(r, d.link_stats)
                          for r, d in enumerate(self.devices)])
        # online tuner (r19): ACCL_TUNE_ONLINE=1 closes the telemetry
        # -> tuner loop over this world (tuning/online.py); unset
        # constructs nothing and dispatch stays bit-identical
        from ..tuning import online as _online

        self.online_tuner = _online.ensure_online_tuner_from_env(self)
        _live_worlds.add(self)  # interpreter-exit safety net

    def start_watchdog(self, **kwargs) -> "_health.Watchdog":
        """Re-arm the watchdog with explicit settings (tests shrink
        timeout_s; a deferred-initialize world arms it after bring-up)."""
        self.watchdog.stop()
        kwargs.setdefault("abort_hook", self._watchdog_abort)
        self.watchdog = _health.Watchdog(
            [a.flight_recorder for a in self.accls
             if a.flight_recorder is not None],
            name="accl-emu", **kwargs).start()
        return self.watchdog

    def _watchdog_abort(self, comm_id: int, report: dict) -> None:
        """ACCL_WATCHDOG_ACTION=abort hook: abort the hung communicator
        with RANK_FAILED, initiated from a rank that actually ARRIVED
        at the stuck gang (the missing rank may be dead and unable to
        propagate anything)."""
        from ..constants import ErrorCode

        hangs = report.get("analysis", {}).get("hangs", [])
        # the hook fires once per hung comm: pick THIS comm's arrived
        # set (hangs[0] may describe a different comm whose arrived
        # ranks include the very rank that is dead here)
        arrived = next((h["arrived"] for h in hangs
                        if h.get("comm") == comm_id), [])
        for r in list(arrived) or list(range(self.nranks)):
            try:
                self.accls[r].abort(comm_id,
                                    error=int(ErrorCode.RANK_FAILED))
                return
            except Exception:  # noqa: BLE001 — try the next survivor
                continue

    def kill_rank(self, rank: int) -> None:
        """Kill-rank chaos: rank's engine goes silent mid-run (egress
        dropped, ingress deaf, local comms aborted with RANK_FAILED)."""
        self.devices[rank].kill()

    def spawn_replacement(self, announce: bool = True) -> "EmuJoiner":
        """Elastic membership: spawn a REAL replacement rank — a fresh
        native engine wired into the live world's hub at the next
        session id, with its own driver brought up on a self-world —
        and (by default) announce it on the membership board so a
        grow-policy recovery supervisor admits it.  The joiner then
        completes the handshake with :meth:`EmuJoiner.join` (engine
        state sync from a sponsor + adoption of the grown comm).
        In-flight traffic on the existing ranks is untouched: the new
        engine only ever speaks the join control plane until the grown
        communicator exists."""
        new_rank = int(self._lib.accl_world_add_rank(self._handle))
        if new_rank < 0:
            raise ACCLError(
                "spawn_replacement: this world's transport cannot grow "
                "(only inproc worlds support live join) or the join "
                "headroom is exhausted")
        device = EmuDevice(self._handle, new_rank, self._lib)
        accl = ACCL(device)
        row = Rank(ip="127.0.0.1", port=0, session=new_rank,
                   max_segment_size=self._egr_rx_buf_size)
        # the joiner brings up on a self-world: its comm 0 is a 1-rank
        # table (usable for local ops only); the join state sync then
        # pads/fences its id space to match the survivors'
        accl.initialize([row], 0, n_egr_rx_bufs=self._n_egr_rx_bufs,
                        egr_rx_buf_size=self._egr_rx_buf_size)
        joiner = EmuJoiner(self, accl, device, new_rank, row)
        if announce:
            joiner.offer = self.board.announce(new_rank, row)
        self.joiners.append(joiner)
        if accl.flight_recorder is not None:
            self.watchdog.add_recorder(accl.flight_recorder)
        return joiner

    def reset_errors(self) -> None:
        """Collective seqn resync after a classified fault: every
        rank's driver + engine state is cleared so the world is
        reusable (the fixture-reuse contract of
        tests/test_fault_injection.py)."""
        for a in self.accls:
            a.reset_errors()

    def resilience_stats(self) -> list:
        """Per-rank engine recovery counters (retransmits, NACKs,
        fenced drops) — the observability of the retransmission lane."""
        return [d.resilience_stats() for d in self.devices]

    def engine_stats(self) -> list:
        """Per-rank full engine telemetry snapshots (r14) — the same
        plane the ACCL_TELEMETRY_INTERVAL_MS sampler polls."""
        return [d.engine_stats() for d in self.devices]

    def link_stats(self) -> dict:
        """Per-rank link rows (r15): global rank -> decoded
        (comm, peer) wire-counter rows."""
        return {r: d.link_stats() for r, d in enumerate(self.devices)}

    def link_matrix(self, comm: int = 0,
                    tenant: Optional[str] = None) -> dict:
        """World-level P×P link traffic matrix over one communicator
        (observability/telemetry.link_matrix doc) — the measured
        per-link bandwidth/congestion input the topology-aware
        selection work (ROADMAP item 2) consumes.  ``tenant`` (r20)
        slices instead by tenant label: the union of every
        communicator labeled that tenant across the world's drivers."""
        from ..observability import telemetry as _telemetry

        if tenant is not None:
            comms = set()
            for a in self.accls:
                comms.update(a.tenant_comm_ids(tenant))
            doc = _telemetry.link_matrix(self.link_stats(),
                                         nranks=self.nranks, comms=comms)
            doc["tenant"] = tenant
            return doc
        return _telemetry.link_matrix(self.link_stats(),
                                      nranks=self.nranks, comm=comm)

    def run(self, fn: Callable, *args) -> list:
        """Run `fn(accl, rank, *args)` on every rank concurrently and
        return per-rank results; exceptions propagate."""
        futures = [
            self._pool.submit(fn, self.accls[r], r, *args)
            for r in range(self.nranks)
        ]
        return [f.result(timeout=120) for f in futures]

    def dump_qps(self, rank: int) -> str:
        """Queue-pair counters for one rank (RDMA rung observability,
        the CoyoteDevice dump analog)."""
        out = ctypes.create_string_buffer(8192)
        n = self._lib.accl_dump_qps(self._handle, rank, out, 8192)
        if n < 0:
            raise ACCLError("world has no RDMA transport")
        return out.value.decode()

    def inject_dgram_fault(self, kind: int) -> None:
        """Arm a one-shot datagram-level fault on the shared hub (drop or
        duplicate the NEXT fragment posted by any rank); only valid for
        the "dgram" transport."""
        rc = self._lib.accl_dgram_fault(self._handle, kind)
        if rc != 0:
            raise ACCLError("world has no datagram transport")

    def close(self) -> None:
        self.watchdog.stop()
        if getattr(self, "online_tuner", None) is not None:
            from ..tuning import online as _online

            # stop the loop before engines die — a mid-teardown A/B
            # measurement would submit against a dying world
            if _online.online_tuner() is self.online_tuner:
                _online.stop_online_tuner()
            else:
                self.online_tuner.stop()
            self.online_tuner = None
        if self.telemetry is not None:
            self.telemetry.stop()  # before shutdown: no poll of a dead world
            self.telemetry = None
        self._pool.shutdown(wait=False)
        if self._handle:
            # lifecycle anchor (r13): after this record, NO successful
            # completion may publish on these ranks — the dump-side
            # invariant analysis.checks.check_teardown_completions
            # verifies (the post-mortem twin of the suite-exit fix)
            for a in self.accls + [j.accl for j in self.joiners]:
                _flight.mark_event(a.flight_recorder, _flight.TEARDOWN_EVENT,
                                   -1, lane="lifecycle")
            # Teardown ordering (the r13 suite-exit segfault fix —
            # docs/debugging.md "The suite-exit segfault"):
            # 1. shutdown: engine threads stop, every pending call
            #    finalizes, so waiter threads parked in accl_wait_call
            #    return within one poll interval;
            # 2. null the device handles UNDER each device's lifecycle
            #    lock: a submission in flight either finished
            #    registering its waiter (joined below) or now observes
            #    None and fails fast — no stale handle survives;
            # 3. join the waiter threads — after this, NO thread can
            #    be inside (or about to enter) the native world;
            # 4. destroy.  If a waiter refuses to die (pathological),
            #    LEAK the native world instead of freeing memory a
            #    live thread may still touch.
            self._lib.accl_world_shutdown(self._handle)
            devices = self.devices + [j.device for j in self.joiners]
            for d in devices:
                with d._lifecycle:
                    d._w = None
            stuck = _join_waiters(devices)
            if stuck:
                get_logger("accl_tpu.emu").warning(
                    "world close: %d waiter thread(s) still alive after "
                    "shutdown — leaking the native world rather than "
                    "freeing memory under a live thread", stuck)
            else:
                self._lib.accl_world_destroy(self._handle)
            self._handle = None

    def __enter__(self) -> "EmuWorld":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class EmuJoiner:
    """Handle on a replacement rank spawned into a live EmuWorld
    (:meth:`EmuWorld.spawn_replacement`): its own native engine,
    driver, and membership-board offer.  The world owns the native
    handle; the joiner's lifetime ends with the world's."""

    def __init__(self, world: EmuWorld, accl: ACCL, device: EmuDevice,
                 rank: int, row: Rank):
        self.world = world
        self.accl = accl
        self.device = device
        self.rank = rank  # session id == global engine index
        self.row = row
        self.offer = None

    def join(self, timeout_s: float = 30.0) -> int:
        """Complete the join handshake (blocks until a survivor's
        admit/grow round claims this offer): engine state sync from
        the sponsor, driver comm-id padding, adoption of the grown
        communicator.  Returns the grown comm id — the first
        communicator this rank can collectively use."""
        from ..resilience.elastic import join_grown_world

        if self.offer is None:
            self.offer = self.world.board.announce(self.rank, self.row)
        return join_grown_world(self.accl, self.offer, timeout_s)
