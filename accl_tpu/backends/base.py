"""Abstract device backend ("CCLO") interface.

Mirrors the role of the reference `CCLO` abstraction: start a call
descriptor asynchronously, expose device memory read/write, and surface
config/retcode/perf-counter state (reference:
driver/xrt/include/accl/cclo.hpp:35-160).
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional

import numpy as np

from ..arithconfig import ArithConfig
from ..buffer import BaseBuffer
from ..communicator import Communicator
from ..constants import CCLOCall
from ..request import Request


class CCLODevice(ABC):
    """One rank's view of the collective engine."""

    # -- call path ----------------------------------------------------
    @abstractmethod
    def start(self, call: CCLOCall, request: Request) -> None:
        """Begin executing a 15-word call descriptor; `request` completes
        asynchronously with the engine retcode + duration."""

    # -- device memory ------------------------------------------------
    @abstractmethod
    def alloc_mem(self, nbytes: int, alignment: int = 64) -> int:
        ...

    @abstractmethod
    def free_mem(self, address: int) -> None:
        ...

    @abstractmethod
    def read_mem(self, address: int, nbytes: int) -> bytes:
        ...

    @abstractmethod
    def write_mem(self, address: int, data: bytes) -> None:
        ...

    # -- buffers ------------------------------------------------------
    @abstractmethod
    def create_buffer(self, length: int, dtype: np.dtype) -> BaseBuffer:
        ...

    # -- configuration ------------------------------------------------
    @abstractmethod
    def setup_rx_buffers(self, n_bufs: int, buf_size: int) -> None:
        """Provision the eager rx buffer pool + rendezvous spare buffers
        (reference: accl.cpp:1147-1212)."""

    @abstractmethod
    def upload_communicator(self, comm: Communicator) -> int:
        """Install a communicator table; returns the id used in call word 2."""

    @abstractmethod
    def upload_arithconfig(self, cfg: ArithConfig) -> int:
        """Install an arithmetic config; returns its table id."""

    # -- kernel streams (the PL-kernel data ports; reference
    # data_to_cclo/data_from_cclo, accl_hls.h:502-543) -----------------
    def push_krnl(self, data: np.ndarray) -> None:
        """Feed operand bytes into the compute-kernel input stream."""
        raise NotImplementedError(f"{type(self).__name__} has no kernel streams")

    def pop_stream(self, strm: int, nbytes: int,
                   timeout_s: float = 10.0) -> Optional[bytes]:
        """Pull one message from a compute output stream."""
        raise NotImplementedError(f"{type(self).__name__} has no kernel streams")

    # -- resilience (accl_tpu/resilience; docs/fault_tolerance.md) ----
    def set_resilience(self, retry_max: int, retry_base_us: int) -> None:
        """Configure the eager NACK-retransmission lane (0 retries =
        off).  Backends without a wire protocol (record-mode lint
        devices, the in-process TPU engine) have nothing to retransmit
        and accept the call as a no-op."""

    def abort_comm(self, comm_id: int, err_bits: int) -> bool:
        """Epoch-tagged communicator abort: finalize every pending call
        on `comm_id` fast with `err_bits` and propagate to peers where
        a control plane exists.  Returns True when the backend handled
        pending-call finalization itself; False lets the driver fall
        back to failing its own tracked requests."""
        return False

    def reset_errors(self) -> None:
        """Seqn resync + transient-state drain after a classified
        fault (collective: every rank of a quiesced world calls it)."""

    def probe_liveness(self, comm_id: int, size: int,
                       window_s: float = 1.0) -> Optional[list]:
        """Per-comm-local-rank liveness via the backend's heartbeat
        plane, or None when the backend has no liveness signal (the
        shrink machinery then treats every rank as alive)."""
        return None

    def sanitizer_domain(self):
        """Identity of the in-process world this device's ranks share,
        or None.  The collective sanitizer (``ACCL_SANITIZE=1``,
        accl_tpu/analysis/sanitizer.py) keys its cross-rank call-
        fingerprint exchange on this: every rank of one gang must
        return the same hashable value *within one process* for the
        pre-dispatch mismatch check to pair them.  Backends whose ranks
        live in different processes must return None — the sanitizer
        then applies single-rank checks only."""
        return None

    def close(self) -> None:
        """Tear down the backend (join threads, close sockets)."""
