"""Device backends implementing the CCLO interface.

Reference analog: the abstract `CCLO` class with FPGADevice / SimDevice /
CoyoteDevice implementations (driver/xrt/include/accl/cclo.hpp:35).
TPU-native backends:

- ``EmuDevice``  (emu.py)  — native C++ collective engine + CPU dataplane
                             over inproc/TCP transport (SimDevice analog).
- ``TpuDevice``  (tpu.py)  — JAX/XLA/Pallas engine over a device mesh
                             (FPGADevice analog; ICI replaces the POEs).
"""

from .base import CCLODevice  # noqa: F401
