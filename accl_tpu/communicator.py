"""Communicator: the rank table and sub-group machinery.

Equivalent of the reference Communicator, which serializes a table of
{ip, port, inbound/outbound sequence numbers, session, max_segment_size}
per rank into device exchange memory and supports readback/dump
(reference: driver/xrt/include/accl/communicator.hpp:34-95,
driver/xrt/src/communicator.cpp:23-117).

The TPU build keeps the same table semantics: the emulator backend uploads
it to the native engine (sequence numbers live device-side and advance per
segment exactly like the reference); the TPU backend maps ranks onto mesh
device coordinates instead of ip:port endpoints.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from .constants import DEFAULT_MAX_EAGER_SIZE


@dataclass
class Rank:
    """One row of the communicator table
    (reference: communicator.hpp:34-39 rank_t)."""

    ip: str = "127.0.0.1"
    port: int = 0
    session: int = 0
    max_segment_size: int = DEFAULT_MAX_EAGER_SIZE
    #: TPU backend: logical device index in the mesh this rank maps to.
    device_index: Optional[int] = None


class Communicator:
    """A group of ranks with a local rank, addressable sessions and
    device-side sequence-number state.

    Unlike the reference (whose table lives in 8KB exchange memory at a
    fixed address, communicator.cpp:23-64), the table here is uploaded to
    the backend which returns an opaque communicator id used in call
    descriptors (word 2 of the ABI).
    """

    #: True only for the dead-slot markers the elastic join protocol
    #: mints (see :meth:`placeholder`); class attribute so every real
    #: communicator answers False with zero per-instance cost
    is_placeholder = False

    #: tenant/lane label for per-tenant observability (r20): class
    #: attribute so unlabeled communicators answer None with zero
    #: per-instance cost; set via ACCL.create_communicator(tenant=...)
    #: or ACCL.set_tenant().  Not part of the wire ABI — the engine
    #: never sees it, only the telemetry plane does.
    tenant = None

    def __init__(self, ranks: Sequence[Rank], local_rank: int, comm_id: int = 0):
        if not 0 <= local_rank < len(ranks):
            raise ValueError(f"local_rank {local_rank} out of range for {len(ranks)} ranks")
        self._ranks = list(ranks)
        self._local_rank = local_rank
        self._id = comm_id

    @classmethod
    def placeholder(cls, comm_id: int) -> "Communicator":
        """Dead-slot marker for the elastic join protocol: a joiner
        pads its comm-id space with these so its NEXT upload lands at
        the same id as the survivors' (the create_communicator ordering
        discipline, applied across a membership change).  Zero ranks;
        the driver fast-fails any call on it and the engine finalizes
        strays with ``COMM_ABORTED | RANK_FAILED``."""
        c = cls.__new__(cls)
        c._ranks = []
        c._local_rank = 0
        c._id = comm_id
        c.is_placeholder = True
        return c

    @property
    def id(self) -> int:
        return self._id

    @property
    def ranks(self) -> list[Rank]:
        return self._ranks

    @property
    def local_rank(self) -> int:
        return self._local_rank

    @property
    def size(self) -> int:
        return len(self._ranks)

    def to_words(self) -> list[int]:
        """Serialize for upload to the native engine: [size, local_rank,
        then per rank: ip(u32), port, session, max_segment_size]
        (layout equivalent of communicator.cpp:23-64)."""
        words = [self.size, self.local_rank]
        for r in self._ranks:
            words.append(_ip_encode(r.ip))
            words.append(r.port)
            words.append(r.session)
            words.append(r.max_segment_size)
        return words

    def split(self, indices: Sequence[int], comm_id: int) -> "Communicator":
        """Create a sub-communicator from a subset of ranks; the local rank
        must be a member (reference: accl.cpp:971-978 create_communicator
        on a subset + test_multicomm test.cpp:676)."""
        if self._local_rank not in indices:
            raise ValueError("local rank must be part of the new communicator")
        new_ranks = [self._ranks[i] for i in indices]
        new_local = list(indices).index(self._local_rank)
        sub = Communicator(new_ranks, new_local, comm_id)
        if self.tenant is not None:
            sub.tenant = self.tenant
        return sub

    def dump(self) -> str:
        """Human-readable table dump
        (reference: accl.cpp:1445-1455 dump_communicator)."""
        ten = f" tenant={self.tenant}" if self.tenant is not None else ""
        lines = [f"communicator {self._id}: size={self.size} local_rank={self._local_rank}{ten}"]
        for i, r in enumerate(self._ranks):
            tag = " (local)" if i == self._local_rank else ""
            lines.append(
                f"  rank {i}: {r.ip}:{r.port} session={r.session} "
                f"max_seg={r.max_segment_size} dev={r.device_index}{tag}"
            )
        return "\n".join(lines)

    def __repr__(self) -> str:
        return f"Communicator(id={self._id}, size={self.size}, local_rank={self._local_rank})"


def _ip_encode(ip: str) -> int:
    """Dotted-quad to u32 (reference: common.cpp:75-90 ip_encode)."""
    parts = ip.split(".")
    if len(parts) != 4:
        return 0
    val = 0
    for p in parts:
        val = (val << 8) | (int(p) & 0xFF)
    return val


def _ip_decode(val: int) -> str:
    return ".".join(str((val >> s) & 0xFF) for s in (24, 16, 8, 0))
