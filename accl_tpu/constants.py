"""ABI constants for the ACCL-TPU framework.

These mirror the reference ACCL host/device ABI so that call descriptors,
error codes and flag algebra stay bit-compatible with the reference driver
(reference: driver/xrt/include/accl/constants.hpp:179-405 and
kernels/cclo/fw/sw_apps/ccl_offload_control/src/ccl_offload_control.h:25-60).
The *implementation* behind these codes is brand new and TPU-native: the
collective engine is a portable C++ library plus a JAX/XLA/Pallas backend,
not a translation of the reference firmware.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass


class Operation(enum.IntEnum):
    """Collective scenario codes carried in word 0 of a call descriptor.

    Values match the reference `operation` enum
    (driver/xrt/include/accl/constants.hpp:191-210).
    """

    config = 0
    copy = 1
    combine = 2
    send = 3
    recv = 4
    bcast = 5
    scatter = 6
    gather = 7
    reduce = 8
    allgather = 9
    allreduce = 10
    reduce_scatter = 11
    barrier = 12
    alltoall = 13
    nop = 255


#: scenarios that form cross-rank gangs in the engines (one instance ==
#: one gang id in the trace); p2p and local ops are single-rank.  Shared
#: by the driver's observability gate (accl.py), the flight-recorder
#: analyzer and the collective sanitizer (accl_tpu/analysis).
GANG_OPERATIONS = frozenset((
    Operation.bcast, Operation.scatter, Operation.gather,
    Operation.allgather, Operation.reduce, Operation.allreduce,
    Operation.reduce_scatter, Operation.alltoall, Operation.barrier,
))


class CfgFunc(enum.IntEnum):
    """Sub-functions of Operation.config
    (reference: constants.hpp:179-185)."""

    reset_periph = 0
    enable_pkt = 1
    set_timeout = 2
    set_max_eager_msg_size = 3
    set_max_rendezvous_msg_size = 4


class ReduceFunction(enum.IntEnum):
    """On-path reduction operator (reference: constants.hpp:216-219)."""

    SUM = 0
    MAX = 1


class DataType(enum.IntEnum):
    """Wire/arithmetic datatypes (reference: constants.hpp:254-262)."""

    none = 0
    int8 = 1
    float16 = 2
    float32 = 3
    float64 = 4
    int32 = 5
    int64 = 6
    # TPU extension: the MXU's native 16-bit float (not in the reference's
    # dtype set, constants.hpp:254-262)
    bfloat16 = 7


#: Width in bits of each DataType (reference: constants.hpp:268-272).
DATA_TYPE_SIZE = {
    DataType.none: 0,
    DataType.int8: 8,
    DataType.float16: 16,
    DataType.float32: 32,
    DataType.float64: 64,
    DataType.int32: 32,
    DataType.int64: 64,
    DataType.bfloat16: 16,
}


class StreamFlags(enum.IntFlag):
    """Streamed-operand markers (reference: constants.hpp:278-282)."""

    NO_STREAM = 0
    OP0_STREAM = 1
    RES_STREAM = 2


class HostFlags(enum.IntFlag):
    """Host-resident-buffer markers (reference: constants.hpp:302-307)."""

    NO_HOST = 0
    OP0_HOST = 1
    OP1_HOST = 2
    RES_HOST = 4


class CompressionFlags(enum.IntFlag):
    """Per-operand / on-the-wire compression markers
    (reference: constants.hpp:327-333)."""

    NO_COMPRESSION = 0
    OP0_COMPRESSED = 1
    OP1_COMPRESSED = 2
    RES_COMPRESSED = 4
    ETH_COMPRESSED = 8


class ErrorCode(enum.IntFlag):
    """26-bit sticky error codes aggregated across the engine
    (reference: constants.hpp:355-387).

    Codes that named FPGA DMA engines in the reference keep their bit
    positions but describe the equivalent stage of the TPU-native engine
    (local memory movers, transport, segmenter, arithmetic lanes).
    """

    COLLECTIVE_OP_SUCCESS = 0
    DMA_MISMATCH_ERROR = 1 << 0
    DMA_INTERNAL_ERROR = 1 << 1
    DMA_DECODE_ERROR = 1 << 2
    DMA_SLAVE_ERROR = 1 << 3
    DMA_NOT_OKAY_ERROR = 1 << 4
    DMA_NOT_END_OF_PACKET_ERROR = 1 << 5
    DMA_NOT_EXPECTED_BTT_ERROR = 1 << 6
    DMA_TIMEOUT_ERROR = 1 << 7
    CONFIG_SWITCH_ERROR = 1 << 8
    DEQUEUE_BUFFER_TIMEOUT_ERROR = 1 << 9
    DEQUEUE_BUFFER_SPARE_BUFFER_STATUS_ERROR = 1 << 10
    RECEIVE_TIMEOUT_ERROR = 1 << 11
    DEQUEUE_BUFFER_SPARE_BUFFER_DMATAG_MISMATCH = 1 << 12
    DEQUEUE_BUFFER_SPARE_BUFFER_INDEX_ERROR = 1 << 13
    COLLECTIVE_NOT_IMPLEMENTED = 1 << 14
    RECEIVE_OFFCHIP_SPARE_BUFF_ID_NOT_VALID = 1 << 15
    EAGER_THRESHOLD_INVALID = 1 << 16
    RENDEZVOUS_THRESHOLD_INVALID = 1 << 17
    DMA_SIZE_ERROR = 1 << 18
    ARITH_ERROR = 1 << 19
    PACK_TIMEOUT_STS_ERROR = 1 << 20
    PACK_SEQ_NUMBER_ERROR = 1 << 21
    COMPRESSION_ERROR = 1 << 22
    KRNL_TIMEOUT_STS_ERROR = 1 << 23
    KRNL_STS_COUNT_ERROR = 1 << 24
    SEGMENTER_EXPECTED_BTT_ERROR = 1 << 25
    DMA_TAG_MISMATCH_ERROR = 1 << 26
    # fault-tolerance extension (no reference analog; mirrored in
    # native/src/common.hpp): the communicator this call ran on was
    # aborted — every pending call on all live ranks finalizes fast
    # with this bit, epoch-fenced against stragglers
    COMM_ABORTED = 1 << 27
    # the abort was triggered by a peer declared dead (watchdog
    # ACCL_WATCHDOG_ACTION=abort or a liveness probe), not by an
    # application-initiated ACCL.abort()
    RANK_FAILED = 1 << 28


#: Bits occupied by engine error codes (bit 0 .. bit 28 inclusive;
#: 27/28 are the fault-tolerance extension).
ERROR_CODE_BITS = 29

#: Internal (non-user-visible) signal used by the engine to re-queue a call
#: whose rendezvous peer has not arrived yet; mirrors the firmware's
#: NOT_READY_ERROR retry path (reference: ccl_offload_control.c:2460-2479).
NOT_READY_ERROR = 1 << 31

#: Driver-internal retcode stamped on a flight record when the collective
#: sanitizer (analysis/sanitizer.py, ACCL_SANITIZE=1) aborts the call
#: BEFORE dispatch: the record must leave the watchdog's in-flight scan
#: (the call will never complete) without claiming engine success.
SANITIZER_ABORT_ERROR = 1 << 30


class TuningKey(enum.IntEnum):
    """Runtime tuning-register keys (reference exchange-memory flat-tree
    thresholds, ccl_offload_control.h:86-90, plus the TPU-backend ring
    crossover).  The ONE authoritative name/value table: the driver
    (`ACCL.set_tuning`), the native engine twin (engine.hpp TuningKey)
    and the TPU backend twin all validate against it, so an unknown key
    raises an ACCLError naming the key and this set instead of silently
    writing nothing (the clear-error contract, r16)."""

    BCAST_FLAT_TREE_MAX_RANKS = 0
    REDUCE_FLAT_TREE_MAX_RANKS = 1
    GATHER_FLAT_TREE_MAX_FANIN = 2
    EGRESS_PIPELINE_DEPTH = 3
    GATHER_FLAT_TREE_MAX_COUNT = 4
    REDUCE_FLAT_TREE_MAX_COUNT = 5
    #: TPU-backend extension: byte threshold above which allreduce /
    #: allgather / reduce_scatter ride the Pallas ring kernels instead
    #: of the XLA HLO collective (backends/tpu.py ring_threshold_bytes,
    #: env default ACCL_RING_THRESHOLD).  The native emulator engine
    #: has no ring/flat crossover register and REJECTS this key.
    RING_THRESHOLD_BYTES = 6


#: key -> name for every tuning register any backend knows; the known
#: set quoted by the clear-error message of `set_tuning` rejections.
TUNING_KEY_NAMES = {int(k): k.name for k in TuningKey}

#: the subset the native emulator engine implements (engine.hpp
#: TuningKey 0..5; RING_THRESHOLD_BYTES is TPU-only)
EMU_TUNING_KEYS = frozenset(
    int(k) for k in TuningKey if k != TuningKey.RING_THRESHOLD_BYTES)

#: the subset the TPU backend implements (flat-tree registers are
#: stored for schedule hints/observability; RING_THRESHOLD_BYTES is
#: live — it reshapes `TpuEngine._gang_plan` signatures)
TPU_TUNING_KEYS = frozenset(int(k) for k in TuningKey)


def unknown_tuning_key_error(key: int, known: "frozenset[int]",
                             backend: str) -> "ACCLError":
    """The shared rejection message: names the offending key and the
    backend's known register set (constants.TuningKey names)."""
    names = ", ".join(f"{k}={TUNING_KEY_NAMES[k]}" for k in sorted(known))
    label = (f"{key} ({TUNING_KEY_NAMES[key]})"
             if key in TUNING_KEY_NAMES else repr(key))
    return ACCLError(
        f"set_tuning: unknown tuning key {label} for the {backend} "
        f"backend — known keys: {names}")


class OperationStatus(enum.IntEnum):
    """Lifecycle of an async request (reference: constants.hpp:226-230)."""

    QUEUED = 0
    EXECUTING = 1
    COMPLETED = 2


class MsgType(enum.IntEnum):
    """Wire message types (reference: kernels/cclo/hls/eth_intf/eth_intf.h:42-45)."""

    EGR_MSG = 0
    RNDZVS_MSG = 1
    RNDZVS_INIT = 2
    RNDZVS_WR_DONE = 3


class NetworkProtocol(enum.IntEnum):
    """Transport family of a backend.  The reference builds one of
    TCP/UDP/RDMA protocol-offload engines into the bitstream
    (constants.hpp:334-338); the TPU build replaces them with the ICI
    mesh (`ICI`) and keeps a socket transport (`SOCKET`) for the CPU
    emulator rung of the test ladder."""

    TCP = 0
    UDP = 1
    RDMA = 2
    SOCKET = 3
    ICI = 4


#: Any-source / any-tag wildcard, and the default tag value.
#: (reference: driver/xrt/include/accl/constants.hpp TAG_ANY = 0xFFFFFFFF)
TAG_ANY = 0xFFFFFFFF

#: Exchange-memory-equivalent defaults (reference: accl.hpp:103-105 and
#: ccl_offload_control.c:27-28).
DEFAULT_EAGER_RX_BUFS = 16
DEFAULT_EAGER_RX_BUF_SIZE = 1024
DEFAULT_MAX_EAGER_SIZE = 32 * 1024
DEFAULT_MAX_RENDEZVOUS_SIZE = 32 * 1024

#: Segmentation ceiling of a single transport packet and of one DMA command
#: (reference: ccl_offload_control.h:51-54).
MAX_PACKETSIZE = 4096
DMA_MAX_BTT = ((1 << 23) - 1) // 64 * 64

#: Width of the streaming datapath the reference moves per cycle; kept as a
#: segment-alignment quantum in the emulator (ccl_offload_control.h:34).
DATAPATH_WIDTH_BYTES = 64

#: Number of rendezvous scratch buffers used by tree reduce
#: (reference: accl.cpp:1190-1212, SPARE1-3).
N_SPARE_BUFFERS = 3


@dataclass
class CCLOCall:
    """The 15-word call descriptor marshalled per collective.

    Field-for-field equivalent of the reference host→device ABI
    (reference: kernels/plugins/hostctrl/hostctrl.cpp:19-63 and
    ccl_offload_control.c:2321-2356): scenario, count, comm, root_src_dst,
    function, msg_tag, arithcfg, compression_flags, stream+host flags,
    and three 64-bit operand addresses (low/high word pairs).
    """

    scenario: Operation = Operation.nop
    count: int = 0
    comm: int = 0  # communicator id
    root_src_dst: int = 0
    function: int = 0  # ReduceFunction or CfgFunc
    tag: int = TAG_ANY
    arithcfg: int = 0  # arithmetic-config table id
    compression_flags: CompressionFlags = CompressionFlags.NO_COMPRESSION
    stream_flags: StreamFlags = StreamFlags.NO_STREAM
    host_flags: HostFlags = HostFlags.NO_HOST
    addr_0: int = 0
    addr_1: int = 0
    addr_2: int = 0
    #: r18 fused-lane hint — NOT part of the 15-word wire ABI (the
    #: reference has no such field; fusion is a backend scheduling
    #: decision).  Riding on the call object keeps it visible to plan
    #: capture/replay and the gang scheduler without widening to_words.
    fused: bool = False

    def to_words(self) -> list[int]:
        """Serialize to the 15-word stream format pushed to the engine."""
        return [
            int(self.scenario),
            int(self.count),
            int(self.comm),
            int(self.root_src_dst),
            int(self.function),
            int(self.tag),
            int(self.arithcfg),
            int(self.compression_flags),
            int(self.stream_flags) | (int(self.host_flags) << 8),
            self.addr_0 & 0xFFFFFFFF,
            (self.addr_0 >> 32) & 0xFFFFFFFF,
            self.addr_1 & 0xFFFFFFFF,
            (self.addr_1 >> 32) & 0xFFFFFFFF,
            self.addr_2 & 0xFFFFFFFF,
            (self.addr_2 >> 32) & 0xFFFFFFFF,
        ]


def error_code_to_str(code: int) -> str:
    """Human-readable decode of a sticky error bitfield
    (reference: constants.hpp:393-405 error_code_to_string)."""
    if code == 0:
        return "COLLECTIVE_OP_SUCCESS"
    names = [e.name for e in ErrorCode if e.value and code & e.value]
    if code & NOT_READY_ERROR:
        names.append("NOT_READY_ERROR")
    if code & SANITIZER_ABORT_ERROR:
        names.append("SANITIZER_ABORT_ERROR")
    return " | ".join(names) if names else f"UNKNOWN_ERROR({code:#x})"


class ACCLError(RuntimeError):
    """Raised by the driver when a collective returns a non-zero retcode
    (reference: accl.cpp:1226-1250 check_return_value)."""

    def __init__(self, message: str, code: int = 0):
        super().__init__(message)
        self.code = code


def env_int(name: str, default: int, minimum: int = None) -> int:
    """Integer env knob with the decodable-error contract: a malformed
    value raises ACCLError NAMING the knob instead of a bare ValueError
    from int() deep inside bring-up.  Scientific notation is accepted
    ("3e7") since operators write budgets that way."""
    import os as _os

    raw = _os.environ.get(name, "")
    if not raw:
        return default
    try:
        val = int(float(raw))
    except ValueError as e:
        raise ACCLError(f"{name}={raw!r} is not a number") from e
    if minimum is not None and val < minimum:
        raise ACCLError(f"{name}={raw!r} must be >= {minimum}")
    return val


def env_float(name: str, default: float, minimum: float = None) -> float:
    """Float twin of :func:`env_int` (same clear-error contract)."""
    import os as _os

    raw = _os.environ.get(name, "")
    if not raw:
        return default
    try:
        val = float(raw)
    except ValueError as e:
        raise ACCLError(f"{name}={raw!r} is not a number") from e
    if minimum is not None and val < minimum:
        raise ACCLError(f"{name}={raw!r} must be >= {minimum}")
    return val
