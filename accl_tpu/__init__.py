"""ACCL-TPU: a TPU-native collective communication framework.

A ground-up rebuild of the capabilities of the reference ACCL (an MPI-like
collective offload library for network-attached FPGAs) for TPUs:

- the same driver API (`ACCL`, buffers, communicators, async requests,
  eager/rendezvous protocols, on-path reduction, wire compression);
- a native C++ collective engine + CPU dataplane emulator, so everything
  is testable without TPU hardware (reference test ladder rung 1);
- a JAX/XLA backend lowering every collective to HLO collectives over the
  ICI mesh, and Pallas kernels for ring collectives / reduction /
  compression lanes;
- an SPMD parallelism layer (data/tensor/pipeline/expert/sequence
  parallelism, ring attention) built on those collectives.
"""

from .accl import ACCL, GLOBAL_COMM  # noqa: F401
from .arithconfig import DEFAULT_ARITH_CONFIG, ArithConfig  # noqa: F401
from .buffer import BaseBuffer, DummyBuffer  # noqa: F401
from .communicator import Communicator, Rank  # noqa: F401
from .constants import (  # noqa: F401
    TAG_ANY,
    ACCLError,
    CCLOCall,
    CfgFunc,
    CompressionFlags,
    DataType,
    ErrorCode,
    HostFlags,
    Operation,
    ReduceFunction,
    StreamFlags,
)
from .device_api import ACCLCommand, ACCLData, DeviceCollectives  # noqa: F401
from .request import Request  # noqa: F401
from .resilience import (  # noqa: F401
    ChaosPlan,
    MembershipBoard,
    RecoveryPolicy,
    RecoverySupervisor,
    RetryPolicy,
)

__version__ = "0.1.0"
