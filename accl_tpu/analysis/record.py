"""Record-mode backend: capture collective programs with zero execution.

:class:`LintDevice` implements the full ``CCLODevice`` surface but
moves no data: every call descriptor completes instantly with retcode
0 and is appended to the rank's
:class:`~accl_tpu.analysis.program.CollectiveProgram`.  Unmodified
driver code — the same ``fn(accl, rank)`` bodies the Emu/Tpu worlds
run — therefore executes in microseconds and leaves behind exactly the
per-rank descriptor streams the static checkers reason about.  (Do not
assert on result DATA under record mode: buffers stay zero.  Scripts
that verify payloads lint via the shadow capture instead —
``scripts/accl_lint.py --mode shadow``.)

:class:`LintWorld` is the EmuWorld-shaped harness over N LintDevices;
``run(fn)`` + ``check()`` is the whole API:

    world = LintWorld(4)
    world.run(my_rank_fn)
    for f in world.check():
        print(f.render())
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ..accl import ACCL
from ..arithconfig import DEFAULT_ARITH_CONFIG, ArithConfig
from ..backends.base import CCLODevice
from ..buffer import BaseBuffer
from ..communicator import Communicator, Rank
from ..constants import CCLOCall, CfgFunc, DataType, Operation
from ..observability import trace as _trace
from ..request import Request
from .checks import check_programs
from .program import CollectiveProgram, RecordedCall

#: reverse map of the default arithcfg table: serialized words -> the
#: (uncompressed, compressed) dtype pair, so the record backend can
#: label calls with real dtype names instead of raw table ids
_WORDS_TO_PAIR = {tuple(cfg.to_words()): pair
                  for pair, cfg in DEFAULT_ARITH_CONFIG.items()}


class LintBuffer(BaseBuffer):
    """Host-only numpy span with a fake (never reused) device address."""

    def __init__(self, host: np.ndarray, device: "LintDevice",
                 address: int, owner: bool = True, host_only: bool = False):
        super().__init__(host, address)
        self._device = device
        self._owner = owner
        self._host_only = host_only

    @property
    def is_host_only(self) -> bool:
        return self._host_only

    def sync_to_device(self) -> None:
        pass

    def sync_from_device(self) -> None:
        pass

    def slice(self, start: int, end: int) -> "LintBuffer":
        itemsize = self._host.itemsize
        return LintBuffer(self._host[start:end], self._device,
                          self._address + start * itemsize, owner=False,
                          host_only=self._host_only)

    def free(self) -> None:
        if self._owner:
            self._device.free_mem(self._address)


class LintDevice(CCLODevice):
    """The no-execution ``CCLODevice``: every start() records + completes."""

    def __init__(self, rank: int, nranks: int,
                 program: Optional[CollectiveProgram] = None):
        self.rank = rank
        self.nranks = nranks
        self.program = program if program is not None \
            else CollectiveProgram(rank, nranks)
        self._arith_pairs: dict = {}   # table id -> (DataType, DataType)
        self._next_arith = 0
        # bump allocator: addresses are NEVER reused, so a freed range
        # referenced later is attributable to exactly one allocation
        self._next_addr = 0x1000
        self.max_eager_size = 0

    # -- call path ----------------------------------------------------
    def start(self, call: CCLOCall, request: Request) -> None:
        op = Operation(call.scenario)
        if op == Operation.config:
            # configuration is driver bring-up, not program content; the
            # eager threshold is kept for protocol-accurate deadlock sim
            if call.function == int(CfgFunc.set_max_eager_msg_size):
                self.max_eager_size = call.count
            request.complete(0, 0.0)
            return
        pair = self._arith_pairs.get(call.arithcfg)
        dtype = pair[0].name if pair else f"arithcfg{call.arithcfg}"
        wire = pair[1].name if pair else dtype
        from ..constants import DATA_TYPE_SIZE

        elem_bytes = (DATA_TYPE_SIZE[pair[0]] // 8) if pair else 4
        rec = request.flight
        self.program.calls.append(RecordedCall(
            index=len(self.program.calls), rank=self.rank, op=op,
            comm=call.comm, root=call.root_src_dst,
            function=call.function, tag=call.tag, count=call.count,
            arithcfg=call.arithcfg,
            compression=int(call.compression_flags),
            stream_flags=int(call.stream_flags), addr0=call.addr_0,
            addr1=call.addr_1, addr2=call.addr_2, dtype=dtype,
            wire_dtype=wire, elem_bytes=elem_bytes,
            run_async=not request.sync, desc=request.description,
            flight_seq=rec.seq if rec is not None else -1,
            request=request))
        if rec is not None:
            rec.mark_dispatched("lint", _trace.now_ns())
        request.complete(0, 0.0)

    # -- device memory (bump allocator, no storage) --------------------
    def alloc_mem(self, nbytes: int, alignment: int = 64) -> int:
        addr = (self._next_addr + alignment - 1) // alignment * alignment
        self._next_addr = addr + max(nbytes, 1)
        self.program.record_alloc(addr, nbytes)
        return addr

    def free_mem(self, address: int) -> None:
        self.program.record_free(address)

    def read_mem(self, address: int, nbytes: int) -> bytes:
        return b"\x00" * nbytes

    def write_mem(self, address: int, data: bytes) -> None:
        pass

    # -- buffers ------------------------------------------------------
    def create_buffer(self, length: int, dtype: np.dtype,
                      host_only: bool = False) -> BaseBuffer:
        host = np.zeros(length, dtype=dtype)
        addr = self.alloc_mem(max(host.nbytes, 1))
        return LintBuffer(host, self, addr, host_only=host_only)

    # -- configuration ------------------------------------------------
    def setup_rx_buffers(self, n_bufs: int, buf_size: int) -> None:
        pass

    def upload_communicator(self, comm: Communicator) -> int:
        # global identity rides the session field of each rank row (the
        # Emu/Tpu worlds populate it the same way), so sub-communicator
        # membership translates back to world ranks for the checkers
        self.program.record_comm(
            comm.id, [r.session for r in comm.ranks])
        return comm.id

    def upload_arithconfig(self, cfg: ArithConfig) -> int:
        aid = self._next_arith
        self._next_arith += 1
        pair = _WORDS_TO_PAIR.get(tuple(cfg.to_words()))
        if pair is not None:
            self._arith_pairs[aid] = pair
        else:  # custom config: label by element widths
            self._arith_pairs[aid] = (DataType.none, DataType.none)
        return aid

    def close(self) -> None:
        pass


class LintWorld:
    """N recorded ranks, EmuWorld-shaped.

    ``run(fn)`` executes ``fn(accl, rank, *args)`` for every rank
    SEQUENTIALLY — record-mode calls never block, so thread-pool
    concurrency would only make the capture nondeterministic.
    """

    def __init__(self, nranks: int, initialize: bool = True):
        self.nranks = nranks
        self.programs = {r: CollectiveProgram(r, nranks)
                         for r in range(nranks)}
        self.devices = [LintDevice(r, nranks, self.programs[r])
                        for r in range(nranks)]
        self.accls = [ACCL(d) for d in self.devices]
        if initialize:
            ranks = [Rank(ip="127.0.0.1", port=0, session=r)
                     for r in range(nranks)]
            for r, a in enumerate(self.accls):
                a.initialize(ranks, r)

    def run(self, fn: Callable, *args) -> list:
        return [fn(self.accls[r], r, *args) for r in range(self.nranks)]

    def check(self) -> list:
        """Run the full static checker suite over the captured programs
        (protocol-accurate eager threshold from the recorded config)."""
        eager = min((d.max_eager_size for d in self.devices), default=0)
        return check_programs(self.programs, eager_threshold=eager)

    def close(self) -> None:
        for a in self.accls:
            a.deinit()

    def __enter__(self) -> "LintWorld":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def record_program(fn: Callable, nranks: int) -> "LintWorld":
    """One-shot convenience: run ``fn(accl, rank)`` under a fresh
    LintWorld and return the world (``.programs`` / ``.check()``)."""
    world = LintWorld(nranks)
    world.run(fn)
    return world
