"""Collective sanitizer: static desync/deadlock linting + runtime checks.

Layout:

- ``program``   — :class:`CollectiveProgram` / :class:`RecordedCall`,
                  the captured per-rank call streams.
- ``record``    — :class:`LintDevice` (the no-execution ``CCLODevice``)
                  and :class:`LintWorld` (EmuWorld-shaped harness).
- ``checks``    — the cross-rank static checker suite
                  (:func:`check_programs`).
- ``findings``  — :class:`Finding` + severity ranking.
- ``sanitizer`` — the ``ACCL_SANITIZE=1`` runtime lane and the shadow
                  :class:`CaptureSession`.

CLI: ``python scripts/accl_lint.py program.py --ranks 4``.

NOTE: this ``__init__`` is import-light and lazy (PEP 562) because the
driver itself imports ``analysis.sanitizer`` — eagerly importing
``record`` here would cycle back into ``accl``.
"""
from __future__ import annotations

_EXPORTS = {
    "CollectiveProgram": "program",
    "RecordedCall": "program",
    "Finding": "findings",
    "sort_findings": "findings",
    "has_errors": "findings",
    "LintBuffer": "record",
    "LintDevice": "record",
    "LintWorld": "record",
    "record_program": "record",
    "check_programs": "checks",
    "check_flight_lifecycle": "checks",
    "check_fence_staleness": "checks",
    "check_teardown_completions": "checks",
    "check_lock_order": "checks",
    "check_stuck_progress": "checks",
    "check_subcomm_interleave": "checks",
    "CaptureSession": "sanitizer",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
