"""Cross-rank static checkers over captured collective programs.

Each checker takes ``programs: dict[rank -> CollectiveProgram]`` and
returns :class:`~accl_tpu.analysis.findings.Finding` objects;
:func:`check_programs` runs the whole suite.  The bug classes are the
ones the flight-recorder/watchdog layer (observability/flight.py)
diagnoses *after* a gang wedges — here they are caught before any
dispatch:

- ``desync-order`` / ``param-mismatch`` — ranks disagree on the Nth
  gang collective of a communicator (op identity, or count/dtype/root/
  function of an agreeing op).  Shares the first-divergent-seq scan
  with :func:`~accl_tpu.observability.flight.merge_flight_dumps`.
- ``desync-missing-call`` — a member issues fewer gang calls than its
  peers: the trailing collectives can never complete.
- ``subcomm-interleave-hazard`` — ranks shared by two overlapping
  sub-communicators issue their collectives in divergent comm order:
  blocking calls deadlock outright, and even async/chunked ones pin
  the shared rx pool against each other (the 8-rank sub-comm
  allgather wedge class — see docs/static_analysis.md).
- ``deadlock-cycle`` / ``p2p-unmatched`` / ``gang-missing-member`` —
  a send/recv matching simulation with a wait-for graph: blocking
  rendezvous sends, blocking recvs and gang barriers advance only when
  their peers arrive; a stuck fixpoint yields the cycle.
- ``root-invalid`` / ``peer-invalid`` — root or src/dst outside the
  communicator.
- ``buffer-overlap`` / ``buffer-alias`` / ``use-after-free`` — operand
  address-range hazards within a call, and calls touching freed
  allocations.
- ``leaked-request`` — async calls whose Request is never waited.
"""
from __future__ import annotations

from typing import Optional

from ..constants import Operation
from ..observability.flight import (
    FENCE_EVENTS,
    PLAN_CAPTURE_EVENT,
    TEARDOWN_EVENT,
    TERMINAL_STATE_NAMES,
    first_divergence,
)
from .findings import ERROR, WARNING, Finding, sort_findings
from .program import CollectiveProgram, RecordedCall, tags_match

#: rooted collectives whose root_src_dst is a comm-local root
_ROOTED = frozenset((Operation.bcast, Operation.scatter,
                     Operation.gather, Operation.reduce))


def _comm_members(programs: dict, comm_id: int) -> list:
    for prog in programs.values():
        if comm_id in prog.comms:
            return prog.comms[comm_id]
    any_prog = next(iter(programs.values()))
    return list(range(any_prog.nranks))


def _gang_by_comm(programs: dict) -> dict:
    """comm id -> {global rank -> ordered gang RecordedCalls}."""
    by_comm: dict = {}
    for r, prog in programs.items():
        for call in prog.calls:
            if call.is_gang:
                by_comm.setdefault(call.comm, {}).setdefault(
                    r, []).append(call)
    return by_comm


# ---------------------------------------------------------------------------
# issue order + parameter agreement
# ---------------------------------------------------------------------------
def check_order_and_params(programs: dict) -> list:
    findings: list = []
    for comm, seqs in sorted(_gang_by_comm(programs).items()):
        members = [m for m in _comm_members(programs, comm)
                   if m in programs]
        if len(members) < 2:
            continue
        per_rank = {r: seqs.get(r, []) for r in members}

        # 1. op-identity divergence: the classic mismatched-order bug
        div = first_divergence(per_rank, lambda c: c.op.name)
        if div is not None:
            i = div["index"]
            detail = ", ".join(
                f"rank {r}: " + (per_rank[r][i].describe()
                                 if i < len(per_rank[r]) else "<nothing>")
                for r in members)
            findings.append(Finding(
                ERROR, "desync-order",
                f"ranks disagree on gang collective #{i} of comm {comm}:"
                f" {detail}",
                hint="every member of a communicator must issue the "
                     "same collectives in the same order; reorder the "
                     "calls or split the groups onto distinct "
                     "communicators",
                comm=comm, ranks=list(members), index=i))
            continue  # later positions cascade from the first slip

        # 2. same op, divergent parameters (count/dtype/root/function/
        #    tag/compression — every field the engines key protocol
        #    decisions on)
        div = first_divergence(per_rank, RecordedCall.signature)
        if div is not None:
            i = div["index"]
            detail = ", ".join(
                f"rank {r}: " + (per_rank[r][i].describe()
                                 if i < len(per_rank[r]) else "<nothing>")
                for r in members)
            findings.append(Finding(
                ERROR, "param-mismatch",
                f"gang collective #{i} of comm {comm} has mismatched "
                f"parameters across ranks: {detail}",
                hint="count, dtype, root, reduce function and "
                     "compression must agree on every rank (each engine "
                     "derives the wire format from its own descriptor)",
                comm=comm, ranks=list(members), index=i))
            continue

        # 3. agreeing prefix but uneven depth: the short rank's peers
        #    hang in the trailing instances
        depths = {r: len(per_rank[r]) for r in members}
        if len(set(depths.values())) > 1:
            lead = max(depths.values())
            behind = {r: n for r, n in depths.items() if n < lead}
            findings.append(Finding(
                ERROR, "desync-missing-call",
                f"uneven gang call counts on comm {comm}: "
                + ", ".join(f"rank {r} issued {n}"
                            for r, n in sorted(depths.items()))
                + f" — the last {lead - min(depths.values())} "
                f"instance(s) can never complete",
                hint="ranks "
                     f"{sorted(behind)} return early (conditional "
                     "collective?); every member must issue the call",
                comm=comm, ranks=sorted(behind), index=min(depths.values())))
    return findings


# ---------------------------------------------------------------------------
# cross-communicator issue order on overlapping sub-communicators
# ---------------------------------------------------------------------------
def check_subcomm_interleave(programs: dict) -> list:
    """Ranks on overlapping communicators must enter them in one
    agreed global order.

    A gang collective in flight holds engine resources (rx-pool
    buffers, lane credits) until every member arrives, so "rank r
    enters comm x before comm y" is an acquisition edge x -> y.  A
    cycle in the cross-rank comm-order graph is the multi-communicator
    ABBA: with blocking calls it deadlocks outright, and with
    async/chunked calls each side's first collective pins the shared
    rx pool against the peer's — the hazard class behind the 8-rank
    concurrent sub-comm allgather wedge (RECEIVE_TIMEOUT with the
    expected segment parked in staging).  Two ranks entering a shared
    comm pair in opposite orders is the 2-cycle; a 2D grid whose rows
    and columns alternate which axis goes first closes longer cycles
    through comms that share only one rank each.  Per-comm order
    agreement is ``desync-order``'s job; this checker compares order
    ACROSS communicators.
    """
    findings: list = []
    # rank -> comm -> index of the rank's first gang call on that comm
    # (position within the rank's gang-call stream: first-issue order,
    # so a trailing world barrier does not fabricate a back edge)
    first_pos: dict = {}
    for r, prog in sorted(programs.items()):
        pos: dict = {}
        k = 0
        for call in prog.calls:
            if not call.is_gang:
                continue
            pos.setdefault(call.comm, k)
            k += 1
        first_pos[r] = pos
    # acquisition edges with one witness rank per edge; a rank only
    # contributes edges between comms it is a member of (it issued on
    # both), so every edge crosses an overlap by construction
    edge_why: dict = {}  # (x, y) -> (rank, gang-pos of x, gang-pos of y)
    for r in sorted(first_pos):
        pos = first_pos[r]
        cs = sorted(pos, key=lambda c: pos[c])
        for i, x in enumerate(cs):
            for y in cs[i + 1:]:
                edge_why.setdefault((x, y), (r, pos[x], pos[y]))

    # 2-cycles first: pairwise divergence on a shared comm pair is the
    # common bug and deserves one precise finding per pair
    flagged: set = set()
    for (x, y), (ra, ax, ay) in sorted(edge_why.items()):
        if x >= y or (y, x) not in edge_why:
            continue
        rb, by, bx = edge_why[(y, x)]
        flagged.update(((x, y), (y, x)))
        findings.append(Finding(
            ERROR, "subcomm-interleave-hazard",
            f"overlapping comms {x} and {y} are entered in divergent "
            f"order: rank {ra} issues comm {x} first (gang call #{ax}, "
            f"then comm {y} at #{ay}), rank {rb} issues comm {y} first "
            f"(gang call #{by}, then comm {x} at #{bx}) — blocking "
            f"calls deadlock, async ones pin the shared rx pool "
            f"against each other (the sub-comm allgather wedge class)",
            hint="issue collectives on overlapping sub-communicators "
                 "in one global order on every rank (e.g. sort by comm "
                 "id, or row-comms before col-comms everywhere)",
            comm=x, ranks=sorted({ra, rb})))
    if findings:
        return findings  # longer cycles through a flagged pair cascade

    # no pairwise divergence: look for a longer cycle (grid shapes
    # whose comm pairs share only one rank each)
    edges: dict = {}
    for (x, y) in edge_why:
        edges.setdefault(x, []).append(y)
        edges.setdefault(y, [])
    cycle = _find_cycle(edges)
    if cycle:
        chain = "; ".join(
            "comm {} before comm {} (rank {}, gang calls #{} -> #{})"
            .format(x, cycle[(k + 1) % len(cycle)],
                    *edge_why[(x, cycle[(k + 1) % len(cycle)])])
            for k, x in enumerate(cycle))
        findings.append(Finding(
            ERROR, "subcomm-interleave-hazard",
            f"communicators {sorted(cycle)} form an acquisition cycle "
            f"across ranks: {chain} — no global comm order exists, so "
            f"the gang windows can interlock (deadlock when blocking, "
            f"rx-pool pinning when chunked/async)",
            hint="pick one global order for overlapping "
                 "sub-communicators (e.g. all row comms before all col "
                 "comms on every rank) so the acquisition graph is "
                 "acyclic",
            comm=min(cycle),
            ranks=sorted({edge_why[(x, cycle[(k + 1) % len(cycle)])][0]
                          for k, x in enumerate(cycle)})))
    return findings


# ---------------------------------------------------------------------------
# root / peer validity
# ---------------------------------------------------------------------------
def check_membership(programs: dict) -> list:
    findings: list = []
    for r, prog in sorted(programs.items()):
        for call in prog.calls:
            P = len(prog.comm_members(call.comm))
            if call.op in _ROOTED and not 0 <= call.root < P:
                findings.append(Finding(
                    ERROR, "root-invalid",
                    f"rank {r} {call.describe()}: root {call.root} is "
                    f"not a member of comm {call.comm} (size {P})",
                    hint="roots are comm-LOCAL ranks: for a "
                         "sub-communicator pass the index within the "
                         "group, not the global rank",
                    comm=call.comm, ranks=[r], index=call.index))
            elif call.is_p2p and not 0 <= call.root < P:
                role = "dst" if call.op == Operation.send else "src"
                findings.append(Finding(
                    ERROR, "peer-invalid",
                    f"rank {r} {call.describe()}: {role} {call.root} "
                    f"outside comm {call.comm} (size {P})",
                    hint="peer ranks are comm-local; check the rank "
                         "arithmetic around world size",
                    comm=call.comm, ranks=[r], index=call.index))
    return findings


# ---------------------------------------------------------------------------
# buffer hazards
# ---------------------------------------------------------------------------
def _overlap(a0: int, n0: int, a1: int, n1: int) -> bool:
    return a0 < a1 + n1 and a1 < a0 + n0


def check_buffer_hazards(programs: dict) -> list:
    findings: list = []
    for r, prog in sorted(programs.items()):
        freed = [(addr, prog.allocs.get(addr, (0, 0))[0], idx)
                 for addr, idx in prog.frees.items()]
        for call in prog.calls:
            ext = call.operand_extents(len(prog.comm_members(call.comm)))
            for i in range(len(ext)):
                for j in range(i + 1, len(ext)):
                    ra, aa, na = ext[i]
                    rb, ab, nb = ext[j]
                    if aa == ab and na == nb:
                        findings.append(Finding(
                            WARNING, "buffer-alias",
                            f"rank {r} {call.describe()}: {ra} and {rb} "
                            f"are the same buffer "
                            f"[{aa:#x}, +{na}) — in-place collectives "
                            f"are backend-dependent",
                            hint="use a distinct result buffer, or "
                                 "verify the backend documents in-place "
                                 "support for this op",
                            comm=call.comm, ranks=[r], index=call.index))
                    elif _overlap(aa, na, ab, nb):
                        findings.append(Finding(
                            ERROR, "buffer-overlap",
                            f"rank {r} {call.describe()}: {ra} "
                            f"[{aa:#x}, +{na}) partially overlaps {rb} "
                            f"[{ab:#x}, +{nb}) — the engine streams "
                            f"both concurrently and will corrupt them",
                            hint="allocate disjoint buffers (watch "
                                 "slice() offsets: the extent is "
                                 "count x elem x fan, not count alone)",
                            comm=call.comm, ranks=[r], index=call.index))
            for _role, addr, nbytes in ext:
                for faddr, fbytes, fidx in freed:
                    if fidx <= call.index and _overlap(addr, nbytes,
                                                       faddr, fbytes):
                        findings.append(Finding(
                            ERROR, "use-after-free",
                            f"rank {r} {call.describe()} reads/writes "
                            f"[{addr:#x}, +{nbytes}) inside buffer "
                            f"[{faddr:#x}, +{fbytes}) freed before "
                            f"call #{call.index}",
                            hint="keep the buffer alive until every "
                                 "call using it (including async ones) "
                                 "has completed",
                            comm=call.comm, ranks=[r], index=call.index))
    return findings


# ---------------------------------------------------------------------------
# leaked async requests
# ---------------------------------------------------------------------------
def check_leaked_requests(programs: dict) -> list:
    findings: list = []
    for r, prog in sorted(programs.items()):
        leaked = [c for c in prog.calls
                  if c.run_async and c.request is not None
                  and not getattr(c.request, "waited", True)]
        for call in leaked:
            seq = (f" (flight seq {call.flight_seq})"
                   if call.flight_seq >= 0 else "")
            findings.append(Finding(
                WARNING, "leaked-request",
                f"rank {r} {call.describe()} was issued run_async but "
                f"its Request is never waited{seq} — errors and "
                f"completion are silently dropped",
                hint="call req.wait() + req.check() (or keep the "
                     "handle and drain it before deinit)",
                comm=call.comm, ranks=[r], index=call.index))
    return findings


# ---------------------------------------------------------------------------
# send/recv matching + wait-for-graph deadlock detection
# ---------------------------------------------------------------------------
class _SimRank:
    __slots__ = ("rank", "calls", "pos", "gang_pos")

    def __init__(self, rank: int, calls: list):
        self.rank = rank
        self.calls = calls
        self.pos = 0
        self.gang_pos: dict = {}  # comm -> next gang instance index

    @property
    def head(self) -> Optional[RecordedCall]:
        return self.calls[self.pos] if self.pos < len(self.calls) else None


def _global_peer(prog: CollectiveProgram, call: RecordedCall) -> int:
    """Translate the comm-local src/dst of a p2p call to a global rank."""
    members = prog.comm_members(call.comm)
    if 0 <= call.root < len(members):
        return members[call.root]
    return -1  # out of range: reported by check_membership


def check_deadlocks(programs: dict, eager_threshold: int = 0) -> list:
    """Simulate p2p matching + gang barriers to a fixpoint.

    Blocking semantics mirror the protocols: a sync send blocks only
    when its payload rides RENDEZVOUS (larger than the recorded eager
    threshold — a buffered eager send completes without the peer); a
    sync recv always blocks on the matching send being posted; a sync
    gang call blocks on every comm member arriving at the same
    instance.  Async calls post and continue.
    """
    findings: list = []
    sims = {r: _SimRank(r, list(prog.calls))
            for r, prog in programs.items()}
    # posted-but-unmatched p2p endpoints: (global_src, global_dst, tag,
    # comm, call) in FIFO order
    pending_sends: list = []
    pending_recvs: list = []
    gang_arrivals: dict = {}  # (comm, instance) -> set of global ranks
    matched_gangs: set = set()

    def match_send(src: int, dst: int, tag: int, comm: int) -> bool:
        for k, (ps, pd, pt, pc, _call) in enumerate(pending_recvs):
            if pc == comm and pd == dst and ps == src \
                    and tags_match(tag, pt):
                pending_recvs.pop(k)
                return True
        return False

    def match_recv(src: int, dst: int, tag: int, comm: int) -> bool:
        for k, (ps, pd, pt, pc, _call) in enumerate(pending_sends):
            if pc == comm and ps == src and pd == dst \
                    and tags_match(pt, tag):
                pending_sends.pop(k)
                return True
        return False

    def post_p2p(sim: _SimRank, call: RecordedCall) -> None:
        prog = programs[sim.rank]
        peer = _global_peer(prog, call)
        if call.op == Operation.send:
            if not match_send(sim.rank, peer, call.tag, call.comm):
                pending_sends.append(
                    (sim.rank, peer, call.tag, call.comm, call))
        else:
            if not match_recv(peer, sim.rank, call.tag, call.comm):
                pending_recvs.append(
                    (peer, sim.rank, call.tag, call.comm, call))

    def blocking(call: RecordedCall, prog: CollectiveProgram) -> bool:
        if call.run_async:
            return False
        if call.is_gang:
            return len(prog.comm_members(call.comm)) > 1
        if call.op == Operation.recv:
            return True
        if call.op == Operation.send:
            # eager sends are buffered by the rx pool; only rendezvous
            # payloads wait for the peer's landing address
            return call.count * call.elem_bytes > eager_threshold
        return False  # local ops never wait on a peer

    def step(sim: _SimRank) -> bool:
        """Advance this rank past every non-blocking head."""
        moved = False
        while True:
            call = sim.head
            if call is None:
                return moved
            prog = programs[sim.rank]
            if blocking(call, prog):
                return moved
            if call.is_p2p:
                post_p2p(sim, call)
            elif call.is_gang:
                i = sim.gang_pos.get(call.comm, 0)
                sim.gang_pos[call.comm] = i + 1
                if len(prog.comm_members(call.comm)) > 1:
                    gang_arrivals.setdefault(
                        (call.comm, i), set()).add(sim.rank)
            sim.pos += 1
            moved = True

    def try_unblock(sim: _SimRank) -> bool:
        call = sim.head
        if call is None:
            return False
        prog = programs[sim.rank]
        if not blocking(call, prog):
            return False
        if call.op == Operation.send:
            peer = _global_peer(prog, call)
            if match_send(sim.rank, peer, call.tag, call.comm):
                sim.pos += 1
                return True
            # peer blocked on the matching recv right now: rendezvous
            psim = sims.get(peer)
            ph = psim.head if psim is not None else None
            if ph is not None and ph.op == Operation.recv \
                    and ph.comm == call.comm \
                    and _global_peer(programs[peer], ph) == sim.rank \
                    and tags_match(call.tag, ph.tag):
                sim.pos += 1
                psim.pos += 1
                return True
            return False
        if call.op == Operation.recv:
            peer = _global_peer(prog, call)
            if match_recv(peer, sim.rank, call.tag, call.comm):
                sim.pos += 1
                return True
            psim = sims.get(peer)
            ph = psim.head if psim is not None else None
            if ph is not None and ph.op == Operation.send \
                    and ph.comm == call.comm \
                    and _global_peer(programs[peer], ph) == sim.rank \
                    and tags_match(ph.tag, call.tag):
                sim.pos += 1
                psim.pos += 1
                return True
            return False
        # gang: all members arrived at this instance?
        members = prog.comm_members(call.comm)
        i = sim.gang_pos.get(call.comm, 0)
        ready = []
        for m in members:
            msim = sims.get(m)
            if msim is None:
                return False  # member has no program: cannot decide
            if m in gang_arrivals.get((call.comm, i), ()):
                continue
            mh = msim.head
            if mh is not None and mh.is_gang and mh.comm == call.comm \
                    and msim.gang_pos.get(call.comm, 0) == i \
                    and not mh.run_async:
                ready.append(msim)
            else:
                return False
        for msim in ready:  # fire: every blocked member advances
            msim.gang_pos[call.comm] = i + 1
            msim.pos += 1
        gang_arrivals.pop((call.comm, i), None)
        matched_gangs.add((call.comm, i))
        return True

    # fixpoint
    progressed = True
    while progressed:
        progressed = False
        for sim in sims.values():
            if step(sim):
                progressed = True
        for sim in sims.values():
            if try_unblock(sim):
                progressed = True

    # -- diagnose the stuck state --------------------------------------
    blocked: dict = {}
    for r, sim in sims.items():
        head = sim.head
        if head is not None:
            blocked[r] = head
    if blocked:
        # ranks co-blocked on the SAME gang instance wait together, not
        # on each other — the wait-for edges must point only at the
        # members who never arrived, or a missing-member hang would be
        # misread as a deadlock cycle among the arrived ranks
        waiting_at: dict = {}
        for r, call in blocked.items():
            if call.is_gang:
                i = sims[r].gang_pos.get(call.comm, 0)
                waiting_at.setdefault((call.comm, i), set()).add(r)

        def gang_arrived(comm: int, i: int) -> set:
            return (gang_arrivals.get((comm, i), set())
                    | waiting_at.get((comm, i), set()))

        # wait-for edges
        edges: dict = {}
        for r, call in blocked.items():
            prog = programs[r]
            if call.is_p2p:
                edges[r] = [_global_peer(prog, call)]
            else:
                i = sims[r].gang_pos.get(call.comm, 0)
                arrived = gang_arrived(call.comm, i)
                edges[r] = [m for m in prog.comm_members(call.comm)
                            if m != r and m not in arrived]
        cycle = _find_cycle(edges)
        if cycle:
            chain = "; ".join(
                f"rank {r} blocked in {blocked[r].describe()} "
                f"(call #{blocked[r].index}) waiting on rank "
                f"{cycle[(k + 1) % len(cycle)]}"
                for k, r in enumerate(cycle))
            findings.append(Finding(
                ERROR, "deadlock-cycle",
                f"circular wait between ranks {cycle}: {chain}",
                hint="break the cycle: make one side async "
                     "(run_async=True) or invert the send/recv order "
                     "on one rank (the classic head-to-head exchange "
                     "fix)",
                ranks=list(cycle)))
        for r, call in sorted(blocked.items()):
            if cycle and r in cycle:
                continue
            if call.is_p2p:
                findings.append(Finding(
                    ERROR, "p2p-unmatched",
                    f"rank {r} blocks forever in {call.describe()} "
                    f"(call #{call.index}): no matching "
                    f"{'recv' if call.op == Operation.send else 'send'}"
                    f" in rank {_global_peer(programs[r], call)}'s "
                    f"program",
                    hint="add the matching call on the peer, or check "
                         "tag/comm values on both sides",
                    comm=call.comm, ranks=[r], index=call.index))
            else:
                i = sims[r].gang_pos.get(call.comm, 0)
                arrived = sorted(gang_arrived(call.comm, i) | {r})
                missing = [m for m in programs[r].comm_members(call.comm)
                           if m not in arrived]
                findings.append(Finding(
                    ERROR, "gang-missing-member",
                    f"rank {r} blocks forever in {call.describe()} "
                    f"(gang instance #{i}): arrived {arrived}, "
                    f"missing {missing}",
                    hint="the missing ranks never issue this "
                         "collective — see the desync findings for "
                         "where their programs diverge",
                    comm=call.comm, ranks=[r], index=call.index))

    # async p2p endpoints nothing ever matched
    for src, dst, _tag, comm, call in pending_sends:
        findings.append(Finding(
            ERROR, "p2p-unmatched",
            f"rank {src} {call.describe()} (call #{call.index}) is "
            f"never received by rank {dst} — the transfer cannot "
            f"complete",
            hint="add the matching recv on the destination rank",
            comm=comm, ranks=[src], index=call.index))
    for src, dst, _tag, comm, call in pending_recvs:
        findings.append(Finding(
            ERROR, "p2p-unmatched",
            f"rank {dst} {call.describe()} (call #{call.index}) has no "
            f"matching send in rank {src}'s program — the recv can "
            f"never be satisfied",
            hint="add the matching send on the source rank, or check "
                 "the src/tag values",
            comm=comm, ranks=[dst], index=call.index))
    return findings


def _find_cycle(edges: dict) -> Optional[list]:
    """One cycle in the wait-for graph, as an ordered rank list."""
    WHITE, GREY, BLACK = 0, 1, 2
    color = {r: WHITE for r in edges}
    stack: list = []

    def dfs(r: int) -> Optional[list]:
        color[r] = GREY
        stack.append(r)
        for nxt in edges.get(r, ()):
            if color.get(nxt, BLACK) == GREY:
                return stack[stack.index(nxt):]
            if color.get(nxt) == WHITE:
                found = dfs(nxt)
                if found:
                    return found
        stack.pop()
        color[r] = BLACK
        return None

    for r in sorted(edges):
        if color[r] == WHITE:
            found = dfs(r)
            if found:
                return found
    return None


# ---------------------------------------------------------------------------
# the suite
# ---------------------------------------------------------------------------
def check_programs(programs: dict,
                   eager_threshold: int = 0) -> list:
    """Run every checker; returns severity-ranked findings.

    ``eager_threshold``: payload bytes below which a blocking send is
    treated as buffered (non-blocking) by the deadlock simulation —
    pass the world's ``max_eager_size`` for protocol-accurate results;
    the default 0 is the conservative all-sends-block reading.
    """
    programs = {r: p for r, p in programs.items() if p is not None}
    if not programs:
        return []
    findings: list = []
    findings += check_order_and_params(programs)
    findings += check_subcomm_interleave(programs)
    findings += check_membership(programs)
    findings += check_buffer_hazards(programs)
    findings += check_leaked_requests(programs)
    findings += check_deadlocks(programs, eager_threshold)
    return sort_findings(findings)


# ---------------------------------------------------------------------------
# happens-before lifecycle checkers over merged flight dumps (r13)
#
# The checkers above reason over captured CollectivePrograms BEFORE
# dispatch; these reason over flight-recorder dumps AFTER the fact —
# production post-mortems.  The driver publishes zero-duration
# lifecycle anchors into the ring (observability.flight.mark_event):
# fences (abort/shrink/grow/reset_errors), plan_capture, and
# engine_teardown — and the checkers verify the happens-before
# invariants whose in-process violations are exactly the races the
# TSan lane catches live:
#
# - ``fence-stale-replay`` — a plan replay COMPLETED successfully on a
#   communicator after its last fence with no re-capture in between:
#   the replay ran against a dead generation (the invalidation
#   contract chaos drill 4 gates in-process, checked from dumps).
# - ``completion-after-teardown`` — a call published a SUCCESSFUL
#   completion after its rank's engine teardown record: some thread
#   was still completing work into a world being destroyed (the r13
#   suite-exit segfault class, as a dump invariant).
# - ``lock-order-inversion`` — two ranks acquired the same pair of
#   communicators (gang collectives held concurrently = locks) in
#   opposite orders: the cross-rank ABBA pattern that deadlocks
#   hierarchical/multi-comm schedules.
# - ``stuck-progress`` — a record parked in a non-terminal state:
#   a submitted call that never finalized (liveness; ERROR when the
#   rank's dump shows engine teardown happened around it).
# ---------------------------------------------------------------------------
def _flight_per_rank(merged) -> dict:
    """rank -> seq-ordered record dicts.  Accepts a merged dump doc
    (``merge_flight_dumps`` output), a single-rank dump, or a path to
    the JSON of either."""
    import json

    if isinstance(merged, str):
        with open(merged) as f:
            merged = json.load(f)
    ranks = merged["ranks"] if "ranks" in merged else [merged]
    return {rd["rank"]: sorted(rd["records"], key=lambda x: x["seq"])
            for rd in ranks}


def check_fence_staleness(merged) -> list:
    """A successful ``plan_replay`` on a comm whose last fence has no
    intervening ``plan_capture``: the replay ran on a generation older
    than the comm's last fence."""
    findings: list = []
    for rank, recs in _flight_per_rank(merged).items():
        fence_seq: dict = {}   # comm -> seq of its last fence
        recaptured: dict = {}  # comm -> a capture happened since
        seen: set = set()
        for rec in recs:
            comm = rec.get("comm", -1)
            name = rec.get("collective", "")
            if comm >= 0:
                seen.add(comm)
            if name in FENCE_EVENTS:
                # comm -1 (reset_errors/teardown) fences every comm
                # that existed at that point; later-minted comms are
                # born clean
                for c in ([comm] if comm >= 0 else sorted(seen)):
                    fence_seq[c] = rec["seq"]
                    recaptured[c] = False
            elif name == PLAN_CAPTURE_EVENT:
                recaptured[comm] = True
            elif (name == "plan_replay" and rec.get("state") == "complete"
                  and rec.get("retcode", 0) == 0
                  and comm in fence_seq and not recaptured.get(comm, True)):
                findings.append(Finding(
                    ERROR, "fence-stale-replay",
                    f"rank {rank}: plan replay (seq {rec['seq']}) "
                    f"completed successfully on comm {comm} after its "
                    f"fence at seq {fence_seq[comm]} with no re-capture "
                    f"in between — the replay ran on a dead generation",
                    hint="every abort/shrink/grow/reset must invalidate "
                         "armed plans; re-capture before replaying "
                         "(CollectivePlan fencing contract)",
                    comm=comm, ranks=[rank], index=rec["seq"]))
    return findings


def check_teardown_completions(merged) -> list:
    """A SUCCESSFUL completion published after the rank's
    ``engine_teardown`` record: a thread was still finishing calls
    into a world being destroyed.  Teardown-finalized calls carry
    COMM_ABORTED (state ``aborted``) and are the sanctioned path."""
    findings: list = []
    for rank, recs in _flight_per_rank(merged).items():
        teardown_t = None
        teardown_seq = None
        for rec in recs:
            if rec.get("collective") == TEARDOWN_EVENT:
                if teardown_t is None or rec["t_complete"] < teardown_t:
                    teardown_t = rec["t_complete"]
                    teardown_seq = rec["seq"]
        if teardown_t is None:
            continue
        for rec in recs:
            if rec.get("collective") == TEARDOWN_EVENT:
                continue
            if (rec.get("state") == "complete"
                    and rec.get("retcode", 0) == 0
                    and rec.get("t_complete", 0) > teardown_t):
                findings.append(Finding(
                    ERROR, "completion-after-teardown",
                    f"rank {rank}: {rec.get('collective')} (seq "
                    f"{rec['seq']}) published a successful completion "
                    f"AFTER the engine teardown record (seq "
                    f"{teardown_seq}) — a completion publisher outlived "
                    f"its engine",
                    hint="teardown must shutdown the engine, join the "
                         "completion publishers, then free (the r13 "
                         "close() ordering); a success after teardown "
                         "means that ordering was violated",
                    comm=rec.get("comm", -1), ranks=[rank],
                    index=rec["seq"]))
    return findings


def check_lock_order(merged) -> list:
    """Cross-rank communicator acquisition order: a gang collective in
    flight is a held lock; a second gang submitted on another comm
    while the first is unfinished is a nested acquisition.  Two ranks
    nesting the same comm pair in OPPOSITE orders is the ABBA pattern
    that deadlocks multi-communicator schedules."""
    findings: list = []
    edges: dict = {}  # (held_comm, wanted_comm) -> {rank: example seq}
    for rank, recs in _flight_per_rank(merged).items():
        gangs = [r for r in recs if r.get("gang")]
        for i, a in enumerate(gangs):
            a_end = a.get("t_complete") or float("inf")
            for b in gangs[i + 1:]:
                if b.get("comm") == a.get("comm"):
                    continue
                if b.get("t_submit", 0) < a_end:  # nested under a
                    edges.setdefault(
                        (a["comm"], b["comm"]), {}).setdefault(
                        rank, (a["seq"], b["seq"]))
    for (x, y), holders in sorted(edges.items()):
        if x >= y or (y, x) not in edges:
            continue
        inverse = edges[(y, x)]
        fwd_only = set(holders) - set(inverse)
        inv_only = set(inverse) - set(holders)
        if fwd_only and inv_only:
            ra = sorted(fwd_only)[0]
            rb = sorted(inv_only)[0]
            findings.append(Finding(
                WARNING, "lock-order-inversion",
                f"rank {ra} holds comm {x} while acquiring comm {y} "
                f"(seqs {holders[ra]}), but rank {rb} nests them in "
                f"the OPPOSITE order (seqs {inverse[rb]}) — the "
                f"cross-rank ABBA pattern that deadlocks when the "
                f"windows overlap",
                hint="acquire communicators in one global order on "
                     "every rank (sort multi-comm gang issue order, "
                     "e.g. by comm id) or barrier between the phases",
                comm=x, ranks=sorted(set(list(fwd_only) + list(inv_only)))))
    return findings


def check_stuck_progress(merged) -> list:
    """Liveness over dumps: every submitted call must finalize.

    A record parked in a non-terminal state (submitted/queued/
    gang_ready/dispatched/recovering — anything outside
    ``TERMINAL_STATE_NAMES``) never published a completion.  In a
    post-mortem dump whose rank carries an ``engine_teardown`` anchor
    that is an ERROR: the world tore down around a call that never
    finalized (the detsched liveness invariant, as a dump check —
    teardown-finalized calls carry COMM_ABORTED and retire
    ``aborted``, so they do NOT trip this).  Without a teardown anchor
    the dump may be a mid-run snapshot, so the finding downgrades to a
    WARNING carrying the in-flight age.
    """
    findings: list = []
    for rank, recs in _flight_per_rank(merged).items():
        has_teardown = any(
            rec.get("collective") == TEARDOWN_EVENT for rec in recs)
        for rec in recs:
            if rec.get("collective") == TEARDOWN_EVENT:
                continue
            state = rec.get("state")
            if state in TERMINAL_STATE_NAMES:
                continue
            age = rec.get("age_us", 0)
            findings.append(Finding(
                ERROR if has_teardown else WARNING, "stuck-progress",
                f"rank {rank}: {rec.get('collective')} (seq "
                f"{rec['seq']}, comm {rec.get('comm', -1)}) never "
                f"finalized — parked in state {state!r} "
                + (f"through engine teardown"
                   if has_teardown else f"for {age} us at dump time")
                + " — a submitted call must retire complete, failed "
                  "or aborted",
                hint="a dispatched-but-never-completed recv whose "
                     "peer made progress is the cross-comm rx-pool "
                     "pinning signature (staged segment, expired "
                     "budget); replay the schedule under "
                     "scripts/model_check.py and check "
                     "engine_wedged_timeouts in the link forensics",
                comm=rec.get("comm", -1), ranks=[rank],
                index=rec["seq"]))
    return findings


def check_flight_lifecycle(merged) -> list:
    """The post-mortem lifecycle suite over merged flight dumps:
    fence-stale replays, completions after teardown, cross-rank
    lock-order inversions, and stuck-progress liveness.  Accepts what
    :func:`~accl_tpu.observability.flight.merge_flight_dumps` produces
    (dict or path) or a single-rank dump."""
    findings: list = []
    findings += check_fence_staleness(merged)
    findings += check_teardown_completions(merged)
    findings += check_lock_order(merged)
    findings += check_stuck_progress(merged)
    return sort_findings(findings)
