"""Finding model shared by the static checkers and the lint CLI."""
from __future__ import annotations

from dataclasses import dataclass, field

#: severity order, most severe first (the CLI prints in this order and
#: exits nonzero iff any ERROR survived)
ERROR = "error"
WARNING = "warning"
INFO = "info"
_SEVERITY_RANK = {ERROR: 0, WARNING: 1, INFO: 2}


@dataclass
class Finding:
    """One checker verdict: what is wrong, where, and how to fix it."""

    severity: str        # ERROR / WARNING / INFO
    code: str            # stable machine id, e.g. "desync-order"
    message: str         # one-sentence statement of the defect
    hint: str = ""       # concrete fix suggestion
    comm: int = -1       # communicator, -1 when not comm-scoped
    ranks: list = field(default_factory=list)  # implicated global ranks
    index: int = -1      # program/gang position, -1 when not positional

    def render(self) -> str:
        loc = []
        if self.comm >= 0:
            loc.append(f"comm {self.comm}")
        if self.index >= 0:
            loc.append(f"call #{self.index}")
        if self.ranks:
            loc.append(f"ranks {self.ranks}")
        where = f" [{', '.join(loc)}]" if loc else ""
        out = f"{self.severity.upper()} {self.code}{where}: {self.message}"
        if self.hint:
            out += f"\n    fix: {self.hint}"
        return out

    def to_dict(self) -> dict:
        return {
            "severity": self.severity, "code": self.code,
            "message": self.message, "hint": self.hint,
            "comm": self.comm, "ranks": self.ranks, "index": self.index,
        }


def sort_findings(findings: list) -> list:
    """Severity-ranked, then comm/position for stable output."""
    return sorted(findings, key=lambda f: (
        _SEVERITY_RANK.get(f.severity, 3), f.comm, f.index, f.code))


def has_errors(findings: list) -> bool:
    return any(f.severity == ERROR for f in findings)
