"""Collective program capture: the data model the sanitizer lints.

A :class:`CollectiveProgram` is one rank's ordered record of every call
descriptor it marshalled — op, communicator, root, count, dtype pair,
operand address ranges, async-ness — plus its communicator tables and
alloc/free event log.  It is produced two ways:

- **record mode** — :class:`~accl_tpu.analysis.record.LintDevice`
  implements the ``CCLODevice`` surface with no data movement and
  captures the program from unmodified driver code (the ACCL+ idea of
  validating collective programs against a simulator before hardware,
  arxiv 2312.11742, taken one step further: no simulation at all, just
  the descriptor stream);
- **shadow mode** — a
  :class:`~accl_tpu.analysis.sanitizer.CaptureSession` records the same
  facts while the calls execute on a real backend.

Both feed :func:`accl_tpu.analysis.checks.check_programs`, which — like
HiCCL's separation of logical collective composition from execution
(arxiv 2408.05962) — reasons about the *composition* symbolically:
issue order, parameter agreement, send/recv matching, buffer hazards.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..constants import (
    GANG_OPERATIONS,
    TAG_ANY,
    CCLOCall,
    CompressionFlags,
    Operation,
)

#: operations that reference no operand memory at all
_NO_OPERAND_OPS = frozenset((Operation.barrier, Operation.nop,
                             Operation.config))

#: per-operation extent multipliers: how many ``count``-element payloads
#: each operand role spans (``P`` = communicator size).  Mirrors the
#: sync_in/sync_out sizing in the driver's collective entry points
#: (accl.py) — the descriptor carries the per-rank count, the engine
#: derives each operand's true span from the op semantics.
def _extent_counts(op: Operation, nranks: int) -> dict:
    P = nranks
    if op in (Operation.scatter, Operation.reduce_scatter):
        return {"op0": P, "op1": 1, "res": 1}
    if op in (Operation.gather, Operation.allgather):
        return {"op0": 1, "op1": 1, "res": P}
    if op == Operation.alltoall:
        return {"op0": P, "op1": 1, "res": P}
    return {"op0": 1, "op1": 1, "res": 1}


@dataclass
class RecordedCall:
    """One captured call descriptor with the facts the checkers need."""

    index: int                    # position in this rank's program
    rank: int                     # issuing rank (global)
    op: Operation
    comm: int
    root: int                     # root_src_dst word (root / src / dst)
    function: int
    tag: int
    count: int
    arithcfg: int
    compression: int
    stream_flags: int
    addr0: int
    addr1: int
    addr2: int
    dtype: str                    # uncompressed dtype label ("float32")
    wire_dtype: str               # compressed/wire dtype label
    elem_bytes: int
    run_async: bool
    desc: str = ""
    flight_seq: int = -1          # flight-recorder seq when armed
    request: Optional[object] = None  # the live Request (leak check)

    @property
    def is_gang(self) -> bool:
        return self.op in GANG_OPERATIONS

    @property
    def is_p2p(self) -> bool:
        return self.op in (Operation.send, Operation.recv)

    def signature(self) -> tuple:
        """Cross-rank agreement fingerprint: every descriptor field all
        ranks of a collective must derive identically.  Deliberately
        EXCLUDED because they are legitimately per-rank: operand
        addresses, per-operand compression bits (only the ROOT of a
        compressed rooted collective marks its buffers — _build's
        flag_operands), stream flags (mem<->stream variants are a
        per-rank choice) and the tag (gang tags are TAG_ANY except the
        root-only RES_STREAM lane).  Of the compression word only the
        WIRE format bit must agree."""
        eth = int(self.compression) & int(CompressionFlags.ETH_COMPRESSED)
        return (self.op.name, self.count, self.root, self.function,
                self.dtype, self.wire_dtype, eth)

    def operand_extents(self, nranks: int) -> list:
        """``(role, address, nbytes)`` for every present operand.
        Dummy operands (address 0) are absent by construction."""
        if self.op in _NO_OPERAND_OPS:
            return []
        mult = _extent_counts(self.op, nranks)
        out = []
        for role, addr in (("op0", self.addr0), ("op1", self.addr1),
                           ("res", self.addr2)):
            if addr != 0:
                out.append((role, addr,
                            self.count * mult[role] * self.elem_bytes))
        return out

    def describe(self) -> str:
        extra = f", root={self.root}" if self.op in (
            Operation.bcast, Operation.scatter, Operation.gather,
            Operation.reduce) else ""
        peer = (f", dst={self.root}" if self.op == Operation.send
                else f", src={self.root}" if self.op == Operation.recv
                else "")
        fn = (f", fn={self.function}" if self.op in (
            Operation.reduce, Operation.allreduce,
            Operation.reduce_scatter, Operation.combine) else "")
        wire = (f", wire={self.wire_dtype}"
                if self.wire_dtype != self.dtype else "")
        return (f"{self.op.name}(count={self.count}, dtype={self.dtype}"
                f"{wire}{extra}{peer}{fn}, comm={self.comm})")


def tags_match(send_tag: int, recv_tag: int) -> bool:
    """Reference tag semantics: a TAG_ANY recv matches any send tag."""
    return recv_tag == TAG_ANY or send_tag == TAG_ANY \
        or send_tag == recv_tag


def call_fingerprint(call: CCLOCall) -> tuple:
    """The runtime sanitizer's cross-rank agreement key for one raw
    descriptor (the record-mode twin is RecordedCall.signature): the
    descriptor words every rank must derive identically.  Excluded as
    legitimately per-rank: operand addresses, per-operand compression
    bits (root-only on compressed rooted collectives), stream flags and
    the tag (root-only RES_STREAM lane) — only the WIRE format
    (arithcfg + ETH bit) must agree."""
    eth = (int(call.compression_flags)
           & int(CompressionFlags.ETH_COMPRESSED))
    return (int(call.scenario), call.count, call.comm, call.root_src_dst,
            call.function, call.arithcfg, eth)


def fingerprint_str(fp: tuple) -> str:
    try:
        name = Operation(fp[0]).name
    except ValueError:  # pragma: no cover — corrupt descriptor
        name = f"op{fp[0]}"
    return (f"{name}(count={fp[1]}, comm={fp[2]}, root/src/dst={fp[3]}, "
            f"fn={fp[4]}, arithcfg={fp[5]}, wire_compressed={bool(fp[6])})")


@dataclass
class CollectiveProgram:
    """One rank's captured call stream + the context to interpret it."""

    rank: int
    nranks: int
    calls: list = field(default_factory=list)
    #: comm id -> list of member GLOBAL ranks (session ids), in comm
    #: rank order — so comm-local roots translate to global ranks
    comms: dict = field(default_factory=dict)
    #: address -> (nbytes, alloc_index); lint allocations never reuse
    #: addresses, so a freed range can be attributed unambiguously
    allocs: dict = field(default_factory=dict)
    #: address -> call index at which it was freed
    frees: dict = field(default_factory=dict)

    def record_comm(self, comm_id: int, members: list) -> None:
        self.comms[comm_id] = list(members)

    def comm_members(self, comm_id: int) -> list:
        """Global ranks of a communicator; unknown comms fall back to
        the world so checks degrade gracefully on partial captures."""
        return self.comms.get(comm_id, list(range(self.nranks)))

    def record_alloc(self, address: int, nbytes: int) -> None:
        self.allocs[address] = (nbytes, len(self.calls))

    def record_free(self, address: int) -> None:
        self.frees[address] = len(self.calls)

    def gang_calls(self, comm_id: int) -> list:
        return [c for c in self.calls if c.is_gang and c.comm == comm_id]

    def to_dict(self) -> dict:
        """JSON-ready rendering (the accl_lint --json payload)."""
        return {
            "rank": self.rank,
            "nranks": self.nranks,
            "comms": {str(k): v for k, v in self.comms.items()},
            "calls": [{
                "index": c.index, "op": c.op.name, "comm": c.comm,
                "root": c.root, "function": c.function, "tag": c.tag,
                "count": c.count, "dtype": c.dtype,
                "wire_dtype": c.wire_dtype, "async": c.run_async,
                "desc": c.desc, "flight_seq": c.flight_seq,
            } for c in self.calls],
        }
