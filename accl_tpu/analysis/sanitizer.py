"""Runtime collective sanitizer (``ACCL_SANITIZE=1``) + shadow capture.

Two consumers share the single driver hook in ``ACCL._execute`` (one
module-bool read on the off path, the same gating discipline as the
trace/flight/metrics observers):

- **runtime sanitizer** — ``ACCL_SANITIZE=1`` (or :func:`set_enabled`)
  turns on per-call hazard checks *before* dispatch: communicator and
  root/peer validity, operand address-range overlap, and — on backends
  whose ranks share the process (emu worlds, the virtual TPU world) —
  a cross-rank **call-fingerprint exchange**: every gang call posts its
  descriptor fingerprint to a shared per-(comm, instance) slot and
  compares against its peers, so a mismatched-order / mismatched-
  parameter program raises an ``ACCLError`` naming BOTH divergent calls
  (tagged with their flight-recorder seqs) instead of wedging until the
  300 s watchdog.  Blocking callers additionally wait for full gang
  agreement (bounded by ``ACCL_SANITIZE_TIMEOUT``, default 60 s), which
  also converts a missing-member hang into an immediate error listing
  the arrived/missing rank sets.

- **shadow capture** — :class:`CaptureSession` records every call into
  per-rank :class:`~accl_tpu.analysis.program.CollectiveProgram` while
  it executes on the real backend; ``scripts/accl_lint.py --mode
  shadow`` uses it to lint unmodified scripts (e.g. ``examples/``)
  whose assertions need real data movement.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..constants import (
    DATA_TYPE_SIZE,
    GANG_OPERATIONS,
    SANITIZER_ABORT_ERROR,
    ACCLError,
    CCLOCall,
    Operation,
)
from ..observability.trace import now_ns
from ..utils.logging import get_logger
from .program import (
    CollectiveProgram,
    RecordedCall,
    call_fingerprint,
    fingerprint_str,
)

#: rooted collectives + p2p: ops whose root_src_dst must be a member
_ROOTED_OR_P2P = frozenset((
    Operation.bcast, Operation.scatter, Operation.gather,
    Operation.reduce, Operation.send, Operation.recv,
))

#: aliased-operand warnings already emitted (bounded): an in-place
#: collective inside a training loop must warn ONCE, not once per step
_warned_aliases: set = set()

# ---------------------------------------------------------------------------
# gating: one module bool on the hot path
# ---------------------------------------------------------------------------
_enabled = os.environ.get("ACCL_SANITIZE", "0") not in ("", "0")
_capture: Optional["CaptureSession"] = None
_active = _enabled


def _recompute() -> None:
    global _active
    _active = _enabled or _capture is not None


def enabled() -> bool:
    """True when the runtime sanitizer lane is on."""
    return _enabled


def set_enabled(on: bool) -> None:
    """Programmatic twin of ``ACCL_SANITIZE`` (tests toggle this)."""
    global _enabled
    _enabled = bool(on)
    _recompute()


def active() -> bool:
    """The driver's gate: sanitizer on OR a capture session installed."""
    return _active


def barrier_timeout_s() -> float:
    raw = os.environ.get("ACCL_SANITIZE_TIMEOUT", "60")
    try:
        return float(raw)
    except ValueError:
        return 60.0


# ---------------------------------------------------------------------------
# shadow capture
# ---------------------------------------------------------------------------
class CaptureSession:
    """Record calls from a real backend into CollectiveProgram maps.

    One session is process-global (installed via ``with`` or
    :meth:`install`); the driver hook feeds it from every ACCL instance,
    and ranks are identified by the session field of their world-comm
    row — the same global identity LintDevice records.
    """

    def __init__(self):
        self.programs: dict = {}
        self.eager_threshold: int = 1 << 62
        self._lock = threading.Lock()

    def install(self) -> "CaptureSession":
        global _capture
        _capture = self
        _recompute()
        return self

    def uninstall(self) -> None:
        global _capture
        if _capture is self:
            _capture = None
            _recompute()

    def __enter__(self) -> "CaptureSession":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def record(self, accl, call: CCLOCall, desc: str, req,
               run_async: bool) -> None:
        if not accl._communicators:
            return  # pre-bring-up local op: no rank identity to file under
        world = accl.communicator(0)
        rank = world.ranks[world.local_rank].session
        pair = accl._arith_pairs.get(call.arithcfg)
        dtype = pair[0].name if pair else f"arithcfg{call.arithcfg}"
        wire = pair[1].name if pair else dtype
        elem = (DATA_TYPE_SIZE[pair[0]] // 8) if pair else 4
        with self._lock:
            prog = self.programs.get(rank)
            if prog is None:
                prog = self.programs[rank] = CollectiveProgram(
                    rank, world.size)
            for comm in accl._communicators:
                if comm.id not in prog.comms:
                    prog.record_comm(
                        comm.id, [r.session for r in comm.ranks])
            self.eager_threshold = min(self.eager_threshold,
                                       accl.max_eager_size)
            rec = req.flight
            prog.calls.append(RecordedCall(
                index=len(prog.calls), rank=rank,
                op=Operation(call.scenario), comm=call.comm,
                root=call.root_src_dst, function=call.function,
                tag=call.tag, count=call.count, arithcfg=call.arithcfg,
                compression=int(call.compression_flags),
                stream_flags=int(call.stream_flags), addr0=call.addr_0,
                addr1=call.addr_1, addr2=call.addr_2, dtype=dtype,
                wire_dtype=wire, elem_bytes=elem, run_async=run_async,
                desc=desc,
                flight_seq=rec.seq if rec is not None else -1,
                request=req))

    def check(self) -> list:
        from .checks import check_programs

        eager = (0 if self.eager_threshold >= 1 << 62
                 else self.eager_threshold)
        return check_programs(self.programs, eager_threshold=eager)


# ---------------------------------------------------------------------------
# cross-rank fingerprint exchange
# ---------------------------------------------------------------------------
class _Slot:
    __slots__ = ("fp", "first_rank", "first_info", "arrived", "poison",
                 "complete", "created")

    def __init__(self, fp: tuple, rank: int, info: str):
        self.fp = fp
        self.first_rank = rank
        self.first_info = info
        self.arrived: set = set()
        self.poison: Optional[tuple] = None  # (rank, info, fp)
        self.complete = False
        self.created = time.monotonic()


_xchg_lock = threading.Lock()
_xchg_cv = threading.Condition(_xchg_lock)
_slots: dict = {}  # (domain, comm, instance) -> _Slot


def _reset_exchange() -> None:
    """Test hook: drop every in-flight agreement slot."""
    with _xchg_cv:
        _slots.clear()
        _xchg_cv.notify_all()


def _sweep_slots_locked() -> None:
    """Expire stale slots (poisoned/timed-out episodes whose members
    never all arrived, partial async instances of dead worlds) so the
    registry stays bounded and a NEW world whose domain key happens to
    collide with a torn-down one (id()/pointer reuse) can never trip
    over a dead world's poisoned slot.  Called under _xchg_lock when
    the registry grows; anything older than 2x the barrier budget has
    already raised on every waiter."""
    if len(_slots) <= 64:
        return
    horizon = time.monotonic() - 2.0 * barrier_timeout_s()
    for key in [k for k, s in _slots.items() if s.created < horizon]:
        del _slots[key]


def _mismatch_error(key: tuple, mine: tuple, mine_info: str,
                    theirs: tuple, their_rank: int,
                    their_info: str) -> ACCLError:
    _domain, comm, idx = key
    return ACCLError(
        f"collective sanitizer: cross-rank call mismatch on comm "
        f"{comm} at gang instance #{idx}: this rank issued "
        f"{fingerprint_str(mine)} [{mine_info}] but rank {their_rank} "
        f"issued {fingerprint_str(theirs)} [{their_info}] — without "
        f"ACCL_SANITIZE this program hangs until the watchdog fires. "
        f"Run scripts/accl_lint.py on the program for the full report.")


def _gang_exchange(domain, comm_id: int, instance: int, fp: tuple,
                   rank: int, nranks: int, info: str,
                   wait: bool) -> None:
    """Post this rank's fingerprint for one gang instance and verify
    agreement; blocking callers wait for the whole gang (bounded)."""
    key = (domain, comm_id, instance)
    with _xchg_cv:
        _sweep_slots_locked()
        slot = _slots.get(key)
        if slot is None:
            slot = _slots[key] = _Slot(fp, rank, info)
        slot.arrived.add(rank)
        if slot.poison is None and fp != slot.fp:
            slot.poison = (rank, info, fp)
        if len(slot.arrived) >= nranks:
            slot.complete = True
            _slots.pop(key, None)
        if slot.poison is not None or slot.complete:
            _xchg_cv.notify_all()
        if slot.poison is not None:
            p_rank, p_info, p_fp = slot.poison
            if p_rank == rank:  # I am the divergent arrival
                raise _mismatch_error(key, fp, info, slot.fp,
                                      slot.first_rank, slot.first_info)
            raise _mismatch_error(key, fp, info, p_fp, p_rank, p_info)
        if not wait or slot.complete:
            return
        deadline = time.monotonic() + barrier_timeout_s()
        while not slot.complete and slot.poison is None:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not _xchg_cv.wait(remaining):
                if slot.complete or slot.poison is not None:
                    break
                missing = [r for r in range(nranks)
                           if r not in slot.arrived]
                raise ACCLError(
                    f"collective sanitizer: gang instance #{instance} "
                    f"on comm {comm_id} ({fingerprint_str(fp)} "
                    f"[{info}]) timed out after "
                    f"{barrier_timeout_s():.0f}s waiting for "
                    f"agreement: arrived ranks "
                    f"{sorted(slot.arrived)}, missing {missing} — the "
                    f"missing ranks never issued this collective "
                    f"(desync or early exit)")
        if slot.poison is not None:
            p_rank, p_info, p_fp = slot.poison
            raise _mismatch_error(key, fp, info, p_fp, p_rank, p_info)


# ---------------------------------------------------------------------------
# per-call runtime checks (the ACCL._execute hook body)
# ---------------------------------------------------------------------------
def _runtime_checks(accl, call: CCLOCall, desc: str, req,
                    run_async: bool) -> None:
    op = Operation(call.scenario)
    comms = accl._communicators
    if not comms:
        # pre-bring-up local-op lane (copy/nop on the implicit world
        # comm): _build keeps it permissive, so must the sanitizer —
        # nothing is resolvable before initialize anyway
        return
    if not 0 <= call.comm < len(comms):
        raise ACCLError(
            f"collective sanitizer: {desc}: unknown communicator id "
            f"{call.comm} (this rank has {len(comms)})")
    comm = comms[call.comm]
    P = comm.size
    if op in _ROOTED_OR_P2P and not 0 <= call.root_src_dst < P:
        role = {"send": "dst", "recv": "src"}.get(op.name, "root")
        raise ACCLError(
            f"collective sanitizer: {desc}: {role} {call.root_src_dst} "
            f"is outside comm {call.comm} (size {P}) — roots and peers "
            f"are comm-LOCAL ranks")

    # operand overlap: partial overlaps corrupt (both streams move
    # concurrently); exact aliasing is backend-dependent -> warn once
    pair = accl._arith_pairs.get(call.arithcfg)
    elem = (DATA_TYPE_SIZE[pair[0]] // 8) if pair else 0
    if elem and call.count:
        rec = RecordedCall(
            index=-1, rank=comm.local_rank, op=op, comm=call.comm,
            root=call.root_src_dst, function=call.function, tag=call.tag,
            count=call.count, arithcfg=call.arithcfg,
            compression=int(call.compression_flags),
            stream_flags=int(call.stream_flags), addr0=call.addr_0,
            addr1=call.addr_1, addr2=call.addr_2, dtype="", wire_dtype="",
            elem_bytes=elem, run_async=run_async)
        ext = rec.operand_extents(P)
        for i in range(len(ext)):
            for j in range(i + 1, len(ext)):
                ra, aa, na = ext[i]
                rb, ab, nb = ext[j]
                if aa == ab and na == nb:
                    dedup = (desc, ra, rb, aa, na)
                    if dedup not in _warned_aliases:
                        if len(_warned_aliases) > 1024:
                            _warned_aliases.clear()
                        _warned_aliases.add(dedup)
                        get_logger("accl_tpu.sanitizer",
                                   rank=comm.local_rank).warning(
                            "%s: %s and %s alias the same buffer "
                            "[%#x, +%d) — in-place behavior is "
                            "backend-dependent", desc, ra, rb, aa, na)
                elif aa < ab + nb and ab < aa + na:
                    raise ACCLError(
                        f"collective sanitizer: {desc}: operand {ra} "
                        f"[{aa:#x}, +{na}) partially overlaps {rb} "
                        f"[{ab:#x}, +{nb}) — the engine would corrupt "
                        f"both")

    # cross-rank fingerprint agreement (in-process worlds only)
    if op in GANG_OPERATIONS and P > 1:
        domain_fn = getattr(accl._device, "sanitizer_domain", None)
        domain = domain_fn() if domain_fn is not None else None
        if domain is not None:
            instance = accl._sanitize_seq.get(call.comm, 0)
            accl._sanitize_seq[call.comm] = instance + 1
            flight = req.flight
            info = (f"rank {comm.local_rank}, flight seq "
                    f"{flight.seq}" if flight is not None
                    else f"rank {comm.local_rank}")
            _gang_exchange(domain, call.comm, instance,
                           call_fingerprint(call), comm.local_rank, P,
                           info, wait=not run_async)


def on_call(accl, call: CCLOCall, desc: str, req,
            run_async: bool) -> None:
    """The one driver hook: feed the capture session and/or run the
    runtime checks.  Only reached when :func:`active` is True."""
    cap = _capture
    if cap is not None:
        cap.record(accl, call, desc, req, run_async)
    if _enabled:
        try:
            _runtime_checks(accl, call, desc, req, run_async)
        except ACCLError:
            # the call will never dispatch: retire its flight record
            # (distinct retcode, not engine success) so the watchdog
            # never reports the aborted call as a hung gang
            rec = req.flight
            if rec is not None and rec.in_flight:
                rec.finish(SANITIZER_ABORT_ERROR, now_ns())
            raise
