"""Model family built on the framework's parallelism layer.

The reference is a collectives library, not a model zoo; these models
exist to exercise every parallelism strategy end-to-end the way the
reference's test/bench applications exercise its collectives
(SURVEY §2.8): a transformer LM composing tensor parallelism (column/row
linears + psum), sequence parallelism (ring attention), data parallelism
(gradient all-reduce with optional wire compression), and optional
pipeline/expert stages.
"""

from .transformer import (  # noqa: F401
    ModelConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
    param_specs,
)
