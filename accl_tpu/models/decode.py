"""Autoregressive inference for the flagship transformer: KV-cache
prefill + single-token decode + greedy generation.

The training stack (models/transformer.py) recomputes every position's
K/V per step; serving recomputes nothing: `prefill` runs the prompt
once and banks each layer's K/V, `decode_step` extends the cache one
token at a time, and `generate` is a jit-compiled prefill + `lax.scan`
over steps (static trip count — XLA-friendly control flow, no
data-dependent Python).

Design notes (TPU-first):
- the cache stores the GROUPED K/V layout ([B, L, G, Dh] with G =
  cfg.kv_heads): under GQA the cache is H/G x smaller — the reason the
  Llama family uses GQA at all — and attention consumes the grouped
  layout directly via a grouped einsum (no per-step expansion in HBM);
- attention against the cache is a dense masked softmax: a single
  decode query row is GEMV-bound (no MXU tiling to win).  Prefill
  uses the same dense path over [Tp, L] scores — right for serving
  prompt lengths; a flash-kernel prefill for very long prompts is the
  training kernel's domain and deliberately out of scope here;
- the cache has a STATIC capacity `max_len` (jit-stable shapes);
  position is a traced scalar and writes use dynamic_update_slice.
  Writing past capacity raises when the position is concrete (eager
  callers); under jit the caller owns the budget — `generate` sizes
  the cache exactly (Tp + max_new) by construction;
- `tp_axis` composes exactly like the training forward (row-parallel
  psum after the attention-out and MLP-down projections) with the
  cache sharded over K/V heads, so a tp-sharded model serves from the
  same shard_map mesh.

The per-block projection/MLP math is SHARED with the training forward
(transformer.block_qkv / block_attn_out / block_mlp) — a change there
propagates here, and the parity contract (tests/test_decode.py:
teacher-forced decode reproduces `forward` position for position, for
every config flavor) locks the seam.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .transformer import (
    ModelConfig,
    _rmsnorm,
    block_attn_out,
    block_mlp,
    block_qkv,
)

NEG_INF = -1e30


def init_kv_cache(cfg: ModelConfig, batch: int, max_len: int) -> dict:
    """Empty cache: per layer K/V of [B, max_len, G, Dh] (grouped
    heads) plus the fill position."""
    shape = (batch, max_len, cfg.kv_heads, cfg.d_head)
    return {
        "pos": jnp.zeros((), jnp.int32),
        "layers": [
            {"k": jnp.zeros(shape, cfg.jdtype),
             "v": jnp.zeros(shape, cfg.jdtype)}
            for _ in range(cfg.n_layers)
        ],
    }


def _grouped_cached_attention(q, kc, vc, pos, window=None):
    """One query block against the cache, grouped-head semantics (no
    K/V expansion).

    q: [B, Tq, H, Dh] (Tq = 1 for decode); kc/vc: [B, L, G, Dh];
    `pos` is the ABSOLUTE position of q's first row; row i attends
    cache slots [0, pos + i] (restricted to the trailing `window`).
    """
    B, Tq, H, Dh = q.shape
    L, G = kc.shape[1], kc.shape[2]
    gr = H // G
    scale = 1.0 / np.sqrt(Dh).astype(np.float32)
    q5 = q.reshape(B, Tq, G, gr, Dh).astype(jnp.float32) * scale
    s = jnp.einsum("bqgrd,blgd->bqgrl", q5, kc.astype(jnp.float32))
    slots = lax.broadcasted_iota(jnp.int32, (Tq, L), 1)
    rows = pos + lax.broadcasted_iota(jnp.int32, (Tq, L), 0)
    keep = slots <= rows
    if window is not None:
        keep = keep & (slots > rows - window)
    s = jnp.where(keep[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bqgrl,blgd->bqgrd", p, vc.astype(jnp.float32))
    return out.reshape(B, Tq, H, Dh)


def prefill(params, tokens, cache: dict, cfg: ModelConfig,
            tp_axis: Optional[str] = None, fused: bool = False):
    """Run the prompt once, filling the cache: tokens [B, Tp] →
    (logits [B, Tp, vocab], cache with pos = prior pos + Tp).
    Continuation prefills (non-zero starting pos) append after the
    already-cached context and attend to all of it."""
    B, Tp = tokens.shape
    pos0 = cache["pos"]
    L = cache["layers"][0]["k"].shape[1]
    if Tp > L:
        raise ValueError(f"prompt length {Tp} exceeds cache capacity {L}")
    if not isinstance(pos0, jax.core.Tracer) and int(pos0) + Tp > L:
        # a clamped dynamic_update_slice would silently OVERWRITE
        # earlier context; fail loudly while the position is concrete
        # (under jit the caller owns the capacity budget — see module
        # docstring)
        raise ValueError(f"prefill past cache capacity: pos {int(pos0)} "
                         f"+ {Tp} > {L}")
    x = params["embed"][tokens].astype(cfg.jdtype)
    positions = (pos0 + jnp.arange(Tp)) if cfg.rope else None
    new_layers = []
    for li, blk in enumerate(params["blocks"]):
        h = _rmsnorm(x, blk["ln1"])
        q, k, v = block_qkv(h, blk, cfg, positions)
        layer = cache["layers"][li]
        kc = lax.dynamic_update_slice(
            layer["k"], k.astype(cfg.jdtype), (0, pos0, 0, 0))
        vc = lax.dynamic_update_slice(
            layer["v"], v.astype(cfg.jdtype), (0, pos0, 0, 0))
        new_layers.append({"k": kc, "v": vc})
        attn = _grouped_cached_attention(
            q, kc, vc, pos0, window=cfg.attn_window).astype(cfg.jdtype)
        x = block_attn_out(x, attn, blk, cfg, tp_axis, fused=fused)
        x = block_mlp(x, blk, cfg, tp_axis, fused=fused)
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("btd,vd->btv", x, params["embed"].astype(cfg.jdtype))
    return logits, {"pos": pos0 + Tp, "layers": new_layers}


def decode_step(params, token, cache: dict, cfg: ModelConfig,
                tp_axis: Optional[str] = None, fused: bool = False):
    """One autoregressive step: token [B] int32 → (logits [B, vocab],
    cache advanced by one)."""
    logits, cache = prefill(params, token[:, None], cache, cfg,
                            tp_axis=tp_axis, fused=fused)
    return logits[:, 0], cache


def _select(lg, key, temperature: float, top_k):
    """Next-token selection from logits [B, vocab]: greedy at
    temperature 0, else temperature-scaled (optionally top-k-truncated)
    categorical sampling."""
    if temperature == 0.0:
        return jnp.argmax(lg, axis=-1).astype(jnp.int32)
    lg = lg.astype(jnp.float32) / temperature
    if top_k is not None:
        # top_k is static and the vocab dim is a static shape, so this
        # validates under jit: an out-of-range top_k would otherwise be
        # index-clamped by JAX and silently degrade to plain
        # temperature sampling
        if not 1 <= top_k <= lg.shape[-1]:
            raise ValueError(
                f"top_k must be in [1, {lg.shape[-1]}], got {top_k}")
        kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
        lg = jnp.where(lg < kth, NEG_INF, lg)
    return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg", "max_new", "tp_axis",
                                   "temperature", "top_k", "fused"))
def _generate_impl(params, prompt, key, cfg: ModelConfig, max_new: int,
                   tp_axis, temperature: float, top_k, fused: bool = False):
    B, Tp = prompt.shape
    cache = init_kv_cache(cfg, B, Tp + max_new)
    logits, cache = prefill(params, prompt, cache, cfg, tp_axis=tp_axis,
                            fused=fused)
    key, sub = jax.random.split(key)
    first = _select(logits[:, -1], sub, temperature, top_k)

    def step(carry, skey):
        token, cache = carry
        lg, cache = decode_step(params, token, cache, cfg,
                                tp_axis=tp_axis, fused=fused)
        nxt = _select(lg, skey, temperature, top_k)
        return (nxt, cache), token

    (_, _), toks = lax.scan(step, (first, cache),
                            jax.random.split(key, max_new))
    return jnp.transpose(toks)  # [max_new, B] -> [B, max_new]


def generate(params, prompt, cfg: ModelConfig, max_new: int,
             tp_axis: Optional[str] = None, temperature: float = 0.0,
             top_k: Optional[int] = None, key=None, fused: bool = False):
    """Autoregressive generation: prompt [B, Tp] int32 → generated
    [B, max_new] int32.  The whole pipeline (prefill + the scan of
    decode steps) is one jit-compiled program; the cache capacity is
    exactly Tp + max_new.

    `temperature=0` (default) is greedy argmax; a positive temperature
    samples from the scaled distribution, optionally truncated to the
    `top_k` most likely tokens — pass a `jax.random` key for
    reproducible sampling (defaults to PRNGKey(0)).

    ``fused=True`` routes the per-block tp combines through the r18
    fused (pipelined) allreduce — meaningful only with a tp axis."""
    if top_k is not None and not 1 <= top_k <= cfg.vocab:
        # validate eagerly (top_k is static): under jit an invalid k
        # would be clamped and silently turn top-k sampling into plain
        # temperature sampling
        raise ValueError(
            f"top_k must be in [1, vocab={cfg.vocab}], got {top_k}")
    if key is None:
        key = jax.random.PRNGKey(0)
    return _generate_impl(params, prompt, key, cfg, max_new, tp_axis,
                          float(temperature), top_k, bool(fused))
