"""Mixture-of-Experts transformer — the expert-parallel model family.

The reference enables expert parallelism purely through its alltoall
collective (SURVEY §2.8: EP "enabled via alltoall",
ccl_offload_control.c:2123-2218); this model is the family built on that
enablement: a switch-style (top-1) MoE transformer whose expert FFNs
shard one-per-member over the ``ep`` mesh axis, with token routing done
by the alltoall dispatch/combine pair in
accl_tpu.parallel.strategies (expert_dispatch/expert_combine).

Dense fallback (``ep_axis=None``) computes every expert locally — the
correctness reference for the distributed path.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.ring_attention import _dense_attention
from ..utils.compat import shard_map as _shard_map
from .transformer import _rmsnorm, sum_count_device_step


@dataclass(frozen=True)
class MoEConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    d_head: int = 32
    d_ff: int = 256
    n_experts: int = 4          # == ep axis size when sharded
    capacity_factor: float = 1.25
    router_aux_weight: float = 1e-2
    dtype: str = "float32"

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


def init_params(rng: np.random.Generator, cfg: MoEConfig) -> dict:
    def g(*shape, scale=0.02):
        return jnp.asarray(rng.standard_normal(shape) * scale, jnp.float32)

    blocks = []
    for _ in range(cfg.n_layers):
        blocks.append({
            "ln1": jnp.ones((cfg.d_model,), jnp.float32),
            "wq": g(cfg.d_model, cfg.n_heads, cfg.d_head),
            "wk": g(cfg.d_model, cfg.n_heads, cfg.d_head),
            "wv": g(cfg.d_model, cfg.n_heads, cfg.d_head),
            "wo": g(cfg.n_heads, cfg.d_head, cfg.d_model),
            "ln2": jnp.ones((cfg.d_model,), jnp.float32),
            "router": g(cfg.d_model, cfg.n_experts),
            # expert FFN banks, leading dim = expert id (sharded over ep)
            "we1": g(cfg.n_experts, cfg.d_model, cfg.d_ff),
            "we2": g(cfg.n_experts, cfg.d_ff, cfg.d_model),
        })
    return {
        "embed": g(cfg.vocab, cfg.d_model),
        "blocks": blocks,
        "ln_f": jnp.ones((cfg.d_model,), jnp.float32),
    }


def param_specs(cfg: MoEConfig, ep: Optional[str] = "ep") -> dict:
    """Expert banks shard over `ep`; everything else is replicated."""
    specs = {
        "embed": P(),
        "ln_f": P(),
        "blocks": [],
    }
    for _ in range(cfg.n_layers):
        specs["blocks"].append({
            "ln1": P(), "wq": P(), "wk": P(), "wv": P(), "wo": P(),
            "ln2": P(), "router": P(),
            "we1": P(ep), "we2": P(ep),
        })
    return specs


def _moe_ffn(h, blk, cfg: MoEConfig, ep_axis: Optional[str],
             capacity: Optional[int] = None, fused: bool = False):
    """Top-1 routed FFN.  h: [B, T, D] -> [B, T, D] + aux loss scalar.

    `capacity` overrides the training-time per-expert budget (ceil of
    B*T*capacity_factor/E).  Serving callers pass the full token count:
    at decode the per-call token count is tiny, so the training formula
    would drop (zero out) any token beyond ~B/E routed to one expert —
    a silent divergence from the dense reference (moe_decode.py).

    ``fused=True`` (r18, ep path only) splits the capacity dimension
    into chunks and pipelines the dispatch/combine alltoalls under the
    expert FFN compute (ops.fused.fused_expert_ffn) — the chunked
    routing is bitwise-equal to dispatch → FFN → combine."""
    B, T, D = h.shape
    x = h.reshape(B * T, D)
    logits = jnp.einsum("nd,de->ne", x, blk["router"].astype(cfg.jdtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]

    # switch-transformer load-balance aux: E * sum_e frac_tokens_e * mean_prob_e
    onehot = jax.nn.one_hot(expert_idx, cfg.n_experts, dtype=jnp.float32)
    aux = cfg.n_experts * jnp.sum(
        jnp.mean(onehot, axis=0) * jnp.mean(probs, axis=0))

    if ep_axis is None:
        # dense reference: run every expert, select by routing one-hot
        y_all = jnp.einsum("nd,edf->enf", x, blk["we1"].astype(cfg.jdtype))
        y_all = jax.nn.gelu(y_all)
        y_all = jnp.einsum("enf,efd->end", y_all,
                           blk["we2"].astype(cfg.jdtype))
        y = jnp.einsum("end,ne->nd", y_all, onehot.astype(cfg.jdtype))
    else:
        from ..parallel.strategies import expert_combine, expert_dispatch
        cap = (capacity if capacity is not None else
               int(np.ceil(B * T * cfg.capacity_factor / cfg.n_experts)))
        # this member's expert bank slice: [1, D, F] under ep sharding
        w1 = blk["we1"].astype(cfg.jdtype)[0]
        w2 = blk["we2"].astype(cfg.jdtype)[0]

        def expert_body(t):
            z = jnp.einsum("nd,df->nf", t, w1)
            z = jax.nn.gelu(z)
            return jnp.einsum("nf,fd->nd", z, w2)

        if fused:
            from ..ops.fused import fused_expert_ffn
            y = fused_expert_ffn(x, expert_idx, expert_body, ep_axis,
                                 capacity=cap)
        else:
            inputs, info = expert_dispatch(x, expert_idx, ep_axis,
                                           capacity=cap)
            y = expert_combine(expert_body(inputs), info, ep_axis)

    y = y * gate.astype(cfg.jdtype)[:, None]
    return y.reshape(B, T, D), aux




def moe_block_qkv(h, blk, cfg: MoEConfig):
    """q/k/v projections of one MoE block — shared by the training
    forward and the serving path (moe_decode.py) so the math cannot
    drift between them (same contract as transformer.block_qkv)."""
    q = jnp.einsum("btd,dhk->bthk", h, blk["wq"].astype(cfg.jdtype))
    k = jnp.einsum("btd,dhk->bthk", h, blk["wk"].astype(cfg.jdtype))
    v = jnp.einsum("btd,dhk->bthk", h, blk["wv"].astype(cfg.jdtype))
    return q, k, v


def moe_block_attn_out(x, attn, blk, cfg: MoEConfig):
    """Attention-out projection + residual (shared with moe_decode)."""
    return x + jnp.einsum("bthk,hkd->btd", attn,
                          blk["wo"].astype(cfg.jdtype))


def forward(params, tokens, cfg: MoEConfig, ep_axis: Optional[str] = None,
            fused: bool = False):
    """Token ids [B, T] -> (logits [B, T, vocab], total aux loss)."""
    x = params["embed"][tokens].astype(cfg.jdtype)
    aux_total = jnp.zeros((), jnp.float32)
    for blk in params["blocks"]:
        h = _rmsnorm(x, blk["ln1"])
        q, k, v = moe_block_qkv(h, blk, cfg)
        attn = _dense_attention(q, k, v, causal=True)
        x = moe_block_attn_out(x, attn, blk, cfg)
        h = _rmsnorm(x, blk["ln2"])
        m, aux = _moe_ffn(h, blk, cfg, ep_axis, fused=fused)
        aux_total = aux_total + aux
        x = x + m
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("btd,vd->btv", x, params["embed"].astype(cfg.jdtype))
    return logits, aux_total


def loss_fn(params, tokens, cfg: MoEConfig, ep_axis: Optional[str] = None,
            fused: bool = False):
    """Next-token cross entropy + router load-balance aux.

    Returns ``(loss_sum, count)`` local to the device — the same
    sum-and-count discipline as transformer.loss_fn, so the train step
    can psum both and scale once.  The aux term is count-weighted
    (``aux * count``) so that after global division by total count the
    result is the token-weighted mean of per-device aux losses."""
    B, T = tokens.shape
    logits, aux = forward(params, tokens, cfg, ep_axis, fused=fused)
    logits = logits.astype(jnp.float32)
    labels = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((B, 1), tokens.dtype)], axis=1)
    valid = jnp.ones((B, T), bool).at[:, -1].set(False)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    count = jnp.sum(valid.astype(jnp.float32))
    loss_sum = jnp.sum(nll) + cfg.router_aux_weight * aux * count
    return loss_sum, count


def make_train_step(mesh, cfg: MoEConfig, lr: float = 1e-3,
                    dp: Optional[str] = "dp", ep: Optional[str] = "ep",
                    fused: bool = False):
    """Jitted SPMD train step: tokens shard over dp, expert banks over
    ep; routing rides the ep alltoall inside the step.

    Returns (step_fn, (param_specs, token_spec))."""
    axes = set(mesh.axis_names)
    dp = dp if dp in axes else None
    ep = ep if ep in axes else None
    if ep is not None and mesh.shape[ep] != cfg.n_experts:
        raise ValueError(
            f"ep axis size {mesh.shape[ep]} != n_experts {cfg.n_experts}")

    specs = param_specs(cfg, ep)
    # tokens shard over BOTH data axes: ep members are data-parallel for
    # the non-expert params, and the ep alltoall exchanges their shards
    tok_spec = P(tuple(a for a in (dp, ep) if a) or None)
    data_axes = tuple(a for a in (dp, ep) if a)

    def device_step(params, tokens):
        # ep-sharded expert banks keep per-shard grads (psummed over dp
        # only by the vma transpose); everything else follows the shared
        # sum-and-count discipline
        return sum_count_device_step(
            lambda p: loss_fn(p, tokens, cfg, ep, fused=fused),
            params, data_axes, lr)

    step = _shard_map(device_step, mesh=mesh,
                         in_specs=(specs, tok_spec),
                         out_specs=(specs, P()))
    return jax.jit(step), (specs, tok_spec)


def shard_params(params, mesh, cfg: MoEConfig, ep: Optional[str] = "ep"):
    ep = ep if ep in set(mesh.axis_names) else None
    specs = param_specs(cfg, ep)
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_s = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    placed = [jax.device_put(p, NamedSharding(mesh, s))
              for p, s in zip(flat_p, flat_s)]
    return jax.tree_util.tree_unflatten(treedef, placed)
