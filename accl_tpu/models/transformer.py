"""Flagship transformer LM exercising the framework end-to-end.

Parallel layout (axes from accl_tpu.parallel.mesh):
- ``dp``: batch sharded; gradients all-reduce (sync_gradients)
- ``tp``: attention heads + MLP hidden sharded; row-parallel psum
- ``sp``: sequence sharded; ring attention rotates K/V over the ring

Pure-pytree parameters (no framework dependency); the train step is
built per-mesh with `shard_map` and jits end-to-end, so XLA schedules
every collective over ICI.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.ring_attention import _dense_attention, ring_attention
from ..utils.compat import axis_size as _axis_size
from ..utils.compat import shard_map as _shard_map


@dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    #: K/V heads (grouped-query attention, the Llama-family layout):
    #: None = n_heads (plain MHA).  Must divide n_heads; each K/V head
    #: serves n_heads/n_kv_heads query heads.  The flash path consumes
    #: the grouped layout expansion-free (ops/flash.py GQA index maps);
    #: dense and ring-SP paths expand K/V per q head.
    n_kv_heads: int | None = None
    d_head: int = 32
    d_ff: int = 512
    dtype: str = "float32"  # compute dtype; bf16 on real TPU
    #: local attention implementation: "dense" (materialized scores) or
    #: "flash" (the Pallas tiled online-softmax kernel, ops/flash.py).
    #: flash requires the local sequence length to divide its blocks.
    attn: str = "dense"
    #: causal SP ring schedule: "contiguous" (natural shards) or
    #: "zigzag" (rank i holds chunk i + mirror 2P-1-i; exact per-hop
    #: load balance — feed tokens permuted by
    #: parallel.ring_attention.zigzag_indices)
    sp_schedule: str = "contiguous"
    #: sliding-window attention (the Mistral-family long-context
    #: tool): each position attends only its trailing `attn_window`
    #: tokens.  flash bounds the grid schedules (forward AND both
    #: backward kernels) to the visible blocks — out-of-window K/V is
    #: never fetched (ops/flash.py); dense applies the band mask.
    #: Under sequence parallelism (contiguous schedule; window <=
    #: T_local) the attention collapses to the local windowed block
    #: plus ONE neighbor hop — O(1) in the ring size
    #: (parallel.ring_attention window= path); zigzag + window raises.
    attn_window: int | None = None
    #: MLP flavor: "gelu" (plain two-matrix) or "swiglu" (the
    #: Llama-family gated unit: silu(x W1) * (x W3) W2 — a third
    #: projection whose gate multiplies elementwise before the down
    #: projection; same tp sharding, hidden dim sharded on both)
    mlp: str = "gelu"
    #: rotary position embeddings (RoPE, the Llama-family positional
    #: scheme): rotate q/k per GLOBAL token position before attention.
    #: Off by default (the parity baselines predate it); under
    #: sequence parallelism each shard rotates by its own global
    #: positions — including the zigzag layout's split chunks — so
    #: distributed and single-device runs agree exactly.
    rope: bool = False
    rope_theta: float = 10000.0
    #: rematerialize each transformer block on the backward pass
    #: (jax.checkpoint): only the block-input residuals stay live; the
    #: per-layer intermediates (d_ff activations, attention
    #: probabilities) are recomputed, at ~1/3 more compute — the
    #: long-context memory lever
    remat: bool = False

    def __post_init__(self):
        if self.attn not in ("dense", "flash"):
            raise ValueError(f"unknown attn implementation {self.attn!r}")
        if self.sp_schedule not in ("contiguous", "zigzag"):
            raise ValueError(f"unknown sp schedule {self.sp_schedule!r}")
        if self.n_kv_heads is not None and (
                self.n_kv_heads <= 0
                or self.n_heads % self.n_kv_heads != 0):
            raise ValueError(
                f"n_kv_heads={self.n_kv_heads} must divide "
                f"n_heads={self.n_heads}")
        if self.attn_window is not None and self.attn_window < 1:
            raise ValueError(f"attn_window={self.attn_window} must be "
                             f">= 1")
        if self.mlp not in ("gelu", "swiglu"):
            raise ValueError(f"unknown mlp flavor {self.mlp!r}")
        if self.rope and self.d_head % 2 != 0:
            raise ValueError(
                f"rope rotates feature PAIRS; d_head={self.d_head} "
                f"must be even")

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


def init_params(rng: np.random.Generator, cfg: ModelConfig) -> dict:
    """Plain-pytree parameters.  TP-shardable leaves carry the head /
    hidden dimension explicitly so PartitionSpecs address it."""
    def g(*shape, scale=0.02):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    D, H, Dh, F = cfg.d_model, cfg.n_heads, cfg.d_head, cfg.d_ff
    G = cfg.kv_heads
    blocks = []
    for _ in range(cfg.n_layers):
        blocks.append({
            "ln1": np.ones(D, np.float32),
            "wq": g(D, H, Dh), "wk": g(D, G, Dh), "wv": g(D, G, Dh),
            "wo": g(H, Dh, D),
            "ln2": np.ones(D, np.float32),
            "w1": g(D, F), "w2": g(F, D),
            **({"w3": g(D, F)} if cfg.mlp == "swiglu" else {}),
        })
    params = {
        "embed": g(cfg.vocab, D, scale=0.02),
        "blocks": blocks,
        "ln_f": np.ones(D, np.float32),
    }
    return jax.tree_util.tree_map(jnp.asarray, params)


def param_specs(cfg: ModelConfig, tp: Optional[str] = "tp") -> dict:
    """PartitionSpec pytree: head/hidden dims sharded over `tp`, the
    rest replicated (None specs).  Under GQA the K/V projections shard
    their (smaller) head axis over the same `tp` — the mesh's tp extent
    must divide n_kv_heads for tensor parallelism to apply."""
    t = tp
    block = {
        "ln1": P(None),
        "wq": P(None, t, None), "wk": P(None, t, None),
        "wv": P(None, t, None),
        "wo": P(t, None, None),
        "ln2": P(None),
        "w1": P(None, t), "w2": P(t, None),
    }
    if cfg.mlp == "swiglu":
        block["w3"] = P(None, t)  # gate shards like w1
    return {
        "embed": P(None, None),
        "blocks": [dict(block) for _ in range(cfg.n_layers)],
        "ln_f": P(None),
    }


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def _rope(x, positions, theta: float):
    """Rotary position embedding on [B, T, h, Dh] (h = that tensor's
    heads; Dh must be even).  Rotates feature pairs (i, i + Dh/2) by
    position-dependent angles — the Llama convention — in f32, cast
    back to the input dtype."""
    B, T, h, Dh = x.shape
    half = Dh // 2
    freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(ang)[None, :, None, :]       # [1, T, 1, half]
    sin = jnp.sin(ang)[None, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def _global_positions(Tl: int, cfg: ModelConfig, sp_axis: Optional[str]):
    """Global token positions of this member's local sequence shard:
    arange outside SP; shard-offset arange for contiguous shards; the
    split (chunk idx, mirror chunk 2P-1-idx) positions for zigzag."""
    if sp_axis is None:
        return jnp.arange(Tl)
    idx = lax.axis_index(sp_axis)
    if cfg.sp_schedule == "zigzag":
        P_ = _axis_size(sp_axis)
        C = Tl // 2
        a = jnp.arange(C)
        return jnp.concatenate([idx * C + a, (2 * P_ - 1 - idx) * C + a])
    return idx * Tl + jnp.arange(Tl)




def block_qkv(h, blk, cfg: ModelConfig, positions):
    """q/k/v projections of one block's normed input (+ RoPE when
    `positions` is given) — ONE definition shared by the training
    forward and the serving path (models/decode.py), so a projection
    change cannot silently break the decode parity contract."""
    q = jnp.einsum("btd,dhk->bthk", h, blk["wq"].astype(cfg.jdtype))
    k = jnp.einsum("btd,dhk->bthk", h, blk["wk"].astype(cfg.jdtype))
    v = jnp.einsum("btd,dhk->bthk", h, blk["wv"].astype(cfg.jdtype))
    if positions is not None:
        # rotate BEFORE any GQA expansion (k carries its own head
        # count; the rotation broadcasts over heads)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
    return q, k, v


def _fused_row_combine(h, w, tp_axis, out_shape, jdtype):
    """r18 fused lane for the row-parallel projections: the matmul and
    the tp allreduce pipeline each other (chunk k+1's wire hop hides
    under chunk k's MXU pass) instead of serializing matmul → psum.
    `h` [..., K] against `w` [K, D]; reduces over `tp_axis`."""
    from ..ops.fused import fused_chunks, fused_matmul_allreduce
    out = fused_matmul_allreduce(h.reshape(-1, h.shape[-1]), w,
                                 axis=tp_axis, use_pallas=False,
                                 chunks=fused_chunks())
    return out.reshape(out_shape).astype(jdtype)


def block_attn_out(x, attn, blk, cfg: ModelConfig, tp_axis,
                   fused: bool = False):
    """Attention-out projection + row-parallel combine + residual
    (shared with models/decode.py).  ``fused=True`` overlaps the tp
    combine with the projection matmul (r18); default is the
    sequential einsum + psum, bit-identical to r17."""
    wo = blk["wo"].astype(cfg.jdtype)
    if fused and tp_axis is not None:
        B, T, H, K = attn.shape
        o = _fused_row_combine(attn.reshape(B * T, H * K),
                               wo.reshape(H * K, -1), tp_axis,
                               (B, T, wo.shape[-1]), cfg.jdtype)
        return x + o
    o = jnp.einsum("bthk,hkd->btd", attn, wo)
    if tp_axis is not None:
        o = lax.psum(o, tp_axis)  # row-parallel combine
    return x + o


def block_mlp(x, blk, cfg: ModelConfig, tp_axis, fused: bool = False):
    """Post-attention MLP (gelu or the Llama-family swiglu) + residual
    (shared with models/decode.py).  ``fused=True`` overlaps the tp
    combine with the down projection (r18)."""
    h = _rmsnorm(x, blk["ln2"])
    m = jnp.einsum("btd,df->btf", h, blk["w1"].astype(cfg.jdtype))
    if cfg.mlp == "swiglu":
        gate = jnp.einsum("btd,df->btf", h,
                          blk["w3"].astype(cfg.jdtype))
        m = jax.nn.silu(m) * gate
    else:
        m = jax.nn.gelu(m)
    w2 = blk["w2"].astype(cfg.jdtype)
    if fused and tp_axis is not None:
        B, T, F = m.shape
        m = _fused_row_combine(m.reshape(B * T, F), w2, tp_axis,
                               (B, T, w2.shape[-1]), cfg.jdtype)
        return x + m
    m = jnp.einsum("btf,fd->btd", m, w2)
    if tp_axis is not None:
        m = lax.psum(m, tp_axis)
    return x + m


def forward(params, tokens, cfg: ModelConfig, tp_axis: Optional[str] = None,
            sp_axis: Optional[str] = None, fused: bool = False):
    """Token ids [B, T_local] → logits [B, T_local, vocab].

    Inside shard_map: `tp_axis` marks head/hidden shards (row-parallel
    psum after attention-out and MLP-down), `sp_axis` marks sequence
    shards (ring attention).  Outside shard_map pass None for both.
    ``fused=True`` pipelines the row-parallel combines under the
    projection matmuls (r18 fused lane; no-op without a tp axis).
    """
    if cfg.sp_schedule == "zigzag" and sp_axis is None:
        # the zigzag layout is only meaningful under sequence
        # parallelism; without it the dense causal mask would silently
        # treat the permuted sequence as natural order
        raise ValueError("sp_schedule='zigzag' requires an sp axis "
                         "(tokens are in zigzag order)")
    x = params["embed"][tokens].astype(cfg.jdtype)  # [B, Tl, D]
    rope_pos = (_global_positions(tokens.shape[1], cfg, sp_axis)
                if cfg.rope else None)

    def block(x, blk):
        h = _rmsnorm(x, blk["ln1"])
        q, k, v = block_qkv(h, blk, cfg, rope_pos)
        if (k.shape[2] != q.shape[2] and sp_axis is None
                and cfg.attn != "flash"):
            # only the local dense path consumes one K/V head per q
            # head; the flash kernel reads the grouped layout in place
            # (K/V index maps share rows across the group) and the ring
            # layer rotates the grouped shards, expanding internally
            # only on its dense reference rung
            from ..parallel.ring_attention import expand_gqa_kv
            k, v = expand_gqa_kv(k, v, q.shape[2])
        if sp_axis is not None:
            if cfg.attn_window is not None and cfg.sp_schedule != \
                    "contiguous":
                raise ValueError(
                    "attn_window under sequence parallelism requires "
                    "the contiguous schedule (the zigzag layout's "
                    "split chunks break the one-neighbor-hop bound)")
            if cfg.attn == "flash":
                raise ValueError(
                    "attn='flash' is the single-shard attention kernel; "
                    "with sequence parallelism the ring layer owns the "
                    "attention schedule — use attn='dense' when sp is on")
            attn = ring_attention(q, k, v, axis=sp_axis, causal=True,
                                  schedule=cfg.sp_schedule,
                                  window=cfg.attn_window)
        elif cfg.attn == "flash":
            from ..ops.flash import flash_attention
            # MXU input format follows the model's activation dtype:
            # bf16 activations get the fast native-rate matmuls, f32
            # configs keep exact f32 numerics (dense-parity contract)
            mxu_dt = (q.dtype if q.dtype in (jnp.bfloat16, jnp.float16)
                      else jnp.float32)
            attn = flash_attention(q, k, v, causal=True,
                                   mxu_dtype=mxu_dt,
                                   window=cfg.attn_window,
                                   interpret=jax.default_backend() != "tpu")
        else:
            attn = _dense_attention(q, k, v, causal=True,
                                    window=cfg.attn_window)
        x = block_attn_out(x, attn, blk, cfg, tp_axis, fused=fused)
        return block_mlp(x, blk, cfg, tp_axis, fused=fused)

    if cfg.remat:
        # rematerialize each block on the backward pass: only the
        # block-input residuals stay live across layers; the per-layer
        # intermediates (d_ff activations, attention probabilities —
        # the bulky part) recompute at ~1/3 more FLOPs (jax.checkpoint
        # over the layer, the knob the big training stacks expose)
        block = jax.checkpoint(block)
    for blk in params["blocks"]:
        x = block(x, blk)
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("btd,vd->btv", x,
                        params["embed"].astype(cfg.jdtype))
    return logits


def loss_fn(params, tokens, cfg: ModelConfig, tp_axis: Optional[str] = None,
            sp_axis: Optional[str] = None, fused: bool = False):
    """Next-token cross entropy.  With sequence parallelism, the label
    for a shard's last position lives on the next shard — fetched with
    one ppermute hop (the pipeline-neighbor send/recv pattern); the
    global last position is masked.  Returns (sum_loss, count) local to
    the device."""
    B, Tl = tokens.shape
    logits = forward(params, tokens, cfg, tp_axis, sp_axis,
                     fused=fused).astype(jnp.float32)
    if sp_axis is not None and cfg.sp_schedule == "zigzag":
        # zigzag layout: the local row is [chunk idx ; chunk 2P-1-idx].
        # Each chunk's last label is its GLOBAL successor's first token:
        #   lo chunk idx    -> chunk idx+1   = rank idx+1's lo-first,
        #                      except idx==P-1 whose successor (chunk P)
        #                      is its OWN hi chunk's first token;
        #   hi chunk 2P-1-idx -> chunk 2P-idx = rank idx-1's hi-first,
        #                      except idx==0 (the global end, masked).
        Pn = _axis_size(sp_axis)
        idx = lax.axis_index(sp_axis)
        C = Tl // 2
        lo, hi = tokens[:, :C], tokens[:, C:]
        from_next_lo = lax.ppermute(  # rank i receives rank i+1's lo[0]
            lo[:, :1], sp_axis, [(i, (i - 1) % Pn) for i in range(Pn)])
        from_prev_hi = lax.ppermute(  # rank i receives rank i-1's hi[0]
            hi[:, :1], sp_axis, [(i, (i + 1) % Pn) for i in range(Pn)])
        lo_end = jnp.where(idx == Pn - 1, hi[:, :1], from_next_lo)
        labels = jnp.concatenate(
            [lo[:, 1:], lo_end, hi[:, 1:], from_prev_hi], axis=1)
        valid = jnp.ones((B, Tl), bool).at[:, -1].set(idx != 0)
    elif sp_axis is not None:
        Pn = _axis_size(sp_axis)
        idx = lax.axis_index(sp_axis)
        nxt_first = lax.ppermute(tokens[:, :1], sp_axis,
                                 [(i, (i - 1) % Pn) for i in range(Pn)])
        labels = jnp.concatenate([tokens[:, 1:], nxt_first], axis=1)
        is_last_shard = idx == Pn - 1
        valid = jnp.ones((B, Tl), bool).at[:, -1].set(
            jnp.logical_not(is_last_shard))
    else:
        labels = jnp.concatenate(
            [tokens[:, 1:], jnp.zeros((B, 1), tokens.dtype)], axis=1)
        valid = jnp.ones((B, Tl), bool).at[:, -1].set(False)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    return jnp.sum(nll), jnp.sum(valid.astype(jnp.float32))


def _mean_grads(loss_closure, params, data_axes):
    """Per-device mean gradients for loss functions returning a LOCAL
    ``(loss_sum, count)`` pair (the sum-and-count discipline).

    Gradients of replicated parameters come back from ``value_and_grad``
    already psummed over the axes they are unvarying on (jax's
    replication-aware vma transpose), and sharded leaves keep per-shard
    grads — so re-reducing here would multiply the gradient by the mesh
    size.  The only remaining work is the global count/loss psum and
    the 1/total normalization.  Returns ``(g_mean, mean_loss)``."""
    (loss_sum, count), grads = jax.value_and_grad(
        loss_closure, has_aux=True)(params)
    total, loss_tot = count, loss_sum
    for a in data_axes:
        total = lax.psum(total, a)
        loss_tot = lax.psum(loss_tot, a)
    denom = jnp.maximum(total, 1.0)
    g_mean = jax.tree_util.tree_map(lambda g: g / denom, grads)
    return g_mean, loss_tot / denom


def sum_count_device_step(loss_closure, params, data_axes, lr):
    """Plain-SGD per-device step over :func:`_mean_grads`.
    Returns ``(new_params, mean_loss)``."""
    g_mean, mean_loss = _mean_grads(loss_closure, params, data_axes)
    new_params = jax.tree_util.tree_map(
        lambda p_, g_: p_ - lr * g_, params, g_mean)
    return new_params, mean_loss


def make_train_step(mesh, cfg: ModelConfig, lr: float = 1e-3,
                    dp: Optional[str] = "dp", tp: Optional[str] = "tp",
                    sp: Optional[str] = "sp", optimizer=None,
                    params=None, check_vma: Optional[bool] = None,
                    fused: bool = False):
    """Build the jitted SPMD train step over `mesh`.

    `check_vma` defaults per backend: on the CPU rung with
    cfg.attn="flash" the Pallas HLO interpreter inside shard_map trips
    jax's vma/dynamic_slice limitation (same caveat as ring_attention's
    flash impl), so the check is disabled there automatically; compiled
    TPU execution keeps it on.  Pass an explicit bool to override.

    Axes not present in the mesh are dropped automatically.  Gradient
    synchronization (the fw allreduce role) happens through jax's
    replication-aware (vma) transposes: parameters enter unvarying over
    dp/sp, so their gradients come back already all-reduced across those
    axes, and tp-sharded leaves keep per-shard gradients — exactly the
    Megatron discipline.  For explicitly compressed gradient sync use
    strategies.sync_gradients in a custom step.

    Default (``optimizer=None``): plain SGD at `lr`; returns
    (step_fn, (param_specs, token_spec)) with
    step_fn(params, tokens) -> (new_params, mean_loss).

    With an optax ``optimizer`` (requires `params` for state-spec
    derivation): optimizer states shard exactly like the parameters
    they mirror (tp-sharded moments stay sharded), and the returned
    bundle is (step_fn, (param_specs, opt_state_specs, token_spec),
    init_opt) with step_fn(params, opt_state, tokens) ->
    (new_params, new_opt_state, mean_loss) and init_opt(params) placing
    a fresh state on the mesh.

    The update runs PER SHARD inside shard_map, so the transform must
    be parameter-local/elementwise (adam, adamw, sgd, momentum, ...).
    Transforms that take cross-parameter statistics — e.g.
    ``clip_by_global_norm`` — would compute them from local tp shards
    and diverge from the single-device result; apply those to the mean
    gradients in a custom step instead."""
    axes = set(mesh.axis_names)
    dp = dp if dp in axes else None
    tp = tp if tp in axes else None
    sp = sp if sp in axes else None
    if cfg.sp_schedule == "zigzag" and sp is None:
        raise ValueError("ModelConfig(sp_schedule='zigzag') needs an 'sp' "
                         "axis in the mesh — zigzag-ordered tokens train "
                         "on wrong labels without the zigzag ring")

    specs = param_specs(cfg, tp)
    tok_spec = P(dp, sp)
    data_axes = tuple(a for a in (dp, sp) if a)
    if check_vma is None:
        check_vma = not (cfg.attn == "flash"
                         and jax.default_backend() != "tpu")

    if optimizer is None:
        def device_step(params, tokens):
            return sum_count_device_step(
                lambda p: loss_fn(p, tokens, cfg, tp, sp, fused=fused),
                params, data_axes, lr)

        step = _shard_map(device_step, mesh=mesh,
                             in_specs=(specs, tok_spec),
                             out_specs=(specs, P()),
                             check_vma=check_vma)
        return jax.jit(step), (specs, tok_spec)

    if params is None:
        raise ValueError("optimizer path needs `params` (a host or "
                         "sharded pytree) to derive optimizer-state "
                         "PartitionSpecs")
    # optimizer states carry whole param-shaped subtrees (adam's mu/nu
    # are literally params-structured trees): substitute the param spec
    # tree for every state node with the params' treedef, replicate the
    # rest (step counts etc.)
    p_treedef = jax.tree_util.tree_structure(params)

    def _params_like(node):
        return jax.tree_util.tree_structure(node) == p_treedef

    state_shapes = jax.eval_shape(optimizer.init, params)
    st_leaves, st_def = jax.tree_util.tree_flatten(
        state_shapes, is_leaf=_params_like)
    opt_specs = jax.tree_util.tree_unflatten(
        st_def, [specs if _params_like(leaf) else P()
                 for leaf in st_leaves])

    import optax as _optax

    def device_step(params, opt_state, tokens):
        g_mean, mean_loss = _mean_grads(
            lambda p: loss_fn(p, tokens, cfg, tp, sp, fused=fused),
            params, data_axes)
        updates, new_state = optimizer.update(g_mean, opt_state, params)
        new_params = _optax.apply_updates(params, updates)
        return new_params, new_state, mean_loss

    step = _shard_map(device_step, mesh=mesh,
                         in_specs=(specs, opt_specs, tok_spec),
                         out_specs=(specs, opt_specs, P()),
                         check_vma=check_vma)

    def init_opt(p):
        return _place(optimizer.init(
            jax.tree_util.tree_map(lambda x: jnp.asarray(x), p)),
            opt_specs, mesh)

    return jax.jit(step), (specs, opt_specs, tok_spec), init_opt


def shard_params(params, mesh, cfg: ModelConfig, tp: Optional[str] = "tp"):
    """Place a host param pytree on the mesh per param_specs."""
    tp = tp if tp in set(mesh.axis_names) else None
    if tp is not None:
        ext = mesh.shape[tp]
        if cfg.kv_heads % ext != 0:
            # fail with the config-level story, not jax's generic
            # "dimension not divisible" from device_put
            raise ValueError(
                f"tensor-parallel extent {ext} must divide "
                f"n_kv_heads={cfg.kv_heads} (the grouped K/V "
                f"projections shard their head axis over {tp!r})")
    specs = param_specs(cfg, tp)
    return _place(params, specs, mesh)


def _place(params, specs, mesh):
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_s = treedef.flatten_up_to(specs)
    placed = [jax.device_put(x, NamedSharding(mesh, s))
              for x, s in zip(flat_p, flat_s)]
    return jax.tree_util.tree_unflatten(treedef, placed)
