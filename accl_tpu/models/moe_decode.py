"""KV-cache inference for the MoE family (models/moe.py).

Same serving structure as models/decode.py — grouped-cache prefill +
single-token decode — with the switch-routed expert FFN in place of
the dense MLP.  Routing at decode time is exactly the training path's
top-1 router on the one live token; the expert-parallel (`ep_axis`)
dispatch/combine works unchanged because expert_dispatch is
shape-agnostic in the token dimension.

Parity contract (tests/test_decode.py::test_moe_*): teacher-forced
decode reproduces models.moe.forward position for position.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .decode import _grouped_cached_attention
from .moe import MoEConfig, _moe_ffn, moe_block_attn_out, moe_block_qkv
from .transformer import _rmsnorm


def init_kv_cache(cfg: MoEConfig, batch: int, max_len: int) -> dict:
    shape = (batch, max_len, cfg.n_heads, cfg.d_head)
    return {
        "pos": jnp.zeros((), jnp.int32),
        "layers": [
            {"k": jnp.zeros(shape, cfg.jdtype),
             "v": jnp.zeros(shape, cfg.jdtype)}
            for _ in range(cfg.n_layers)
        ],
    }


def prefill(params, tokens, cache: dict, cfg: MoEConfig,
            ep_axis: Optional[str] = None):
    """tokens [B, Tp] → (logits [B, Tp, vocab], aux, filled cache)."""
    B, Tp = tokens.shape
    pos0 = cache["pos"]
    L = cache["layers"][0]["k"].shape[1]
    if Tp > L:
        raise ValueError(f"prompt length {Tp} exceeds cache capacity {L}")
    if not isinstance(pos0, jax.core.Tracer) and int(pos0) + Tp > L:
        raise ValueError(f"prefill past cache capacity: pos {int(pos0)} "
                         f"+ {Tp} > {L}")
    x = params["embed"][tokens].astype(cfg.jdtype)
    aux_total = jnp.zeros((), jnp.float32)
    new_layers = []
    for li, blk in enumerate(params["blocks"]):
        h = _rmsnorm(x, blk["ln1"])
        q, k, v = moe_block_qkv(h, blk, cfg)
        layer = cache["layers"][li]
        kc = lax.dynamic_update_slice(
            layer["k"], k.astype(cfg.jdtype), (0, pos0, 0, 0))
        vc = lax.dynamic_update_slice(
            layer["v"], v.astype(cfg.jdtype), (0, pos0, 0, 0))
        new_layers.append({"k": kc, "v": vc})
        attn = _grouped_cached_attention(q, kc, vc, pos0).astype(cfg.jdtype)
        x = moe_block_attn_out(x, attn, blk, cfg)
        h = _rmsnorm(x, blk["ln2"])
        # drop-free serving capacity (see module docstring)
        m, aux = _moe_ffn(h, blk, cfg, ep_axis, capacity=B * Tp)
        aux_total = aux_total + aux
        x = x + m
    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("btd,vd->btv", x, params["embed"].astype(cfg.jdtype))
    return logits, aux_total, {"pos": pos0 + Tp, "layers": new_layers}


def decode_step(params, token, cache: dict, cfg: MoEConfig,
                ep_axis: Optional[str] = None):
    """token [B] int32 → (logits [B, vocab], cache advanced by one)."""
    logits, _aux, cache = prefill(params, token[:, None], cache, cfg,
                                  ep_axis=ep_axis)
    return logits[:, 0], cache
